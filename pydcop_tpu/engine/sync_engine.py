"""The synchronous engine: drives a solver's jitted step to convergence.

This replaces the reference's entire thread/queue/HTTP runtime for the
data plane (SURVEY.md §3.3): instead of agents exchanging messages one at a
time through per-agent priority queues, the engine runs chunks of algorithm
cycles inside a single ``lax.while_loop`` on device, syncing back to the
host only between chunks (for convergence checks, timeout and metric
collection).
"""

import time
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ._cache import enable_persistent_cache
from .solver import ArraySolver, RunResult

#: problems whose per-cycle work is below this many table cells run on
#: the solver's pure-numpy host mirror instead of compiling: an XLA
#: trace+compile costs seconds, a 10-variable cycle costs microseconds
#: (the reference solves its CI instances inside 3-5 s timeouts —
#: tests/api/test_api_solve.py:36-93 — compile-free)
HOST_ENGINE_CELLS = 50_000


def _host_tree(tree):
    from ..robustness.checkpoint import tree_to_host

    return tree_to_host(tree)


class SyncEngine:
    def __init__(self, solver: ArraySolver, chunk_size: int = 32):
        enable_persistent_cache()
        self._solver = solver
        self._chunk = chunk_size

        def run_chunk(state, limit):
            def cond(s):
                return jnp.logical_and(
                    jnp.logical_not(s["finished"]), s["cycle"] < limit
                )

            return jax.lax.while_loop(cond, solver.step, state)

        self._run_chunk = jax.jit(run_chunk)
        self._run_chunk_metrics = None   # built on first telemetry run
        self._aot = {}                   # AOT spans path, per signature
        self._cost = jax.jit(solver.cost)
        self._idx = jax.jit(solver.assignment_indices)
        #: spans / HLO census of the most recent telemetry run
        self.last_spans = {}
        self.last_compile_stats = {}

    @property
    def solver(self) -> ArraySolver:
        return self._solver

    def _metrics_chunk_fn(self):
        """The telemetry chunk: the same while-loop, carrying the
        metric planes in a (state, planes) tuple so solver ``step``
        implementations never see (or need to preserve) the extra
        keys.  Per cycle: selection flips (via the solver's own
        ``assignment_indices`` decode), message residual ``max|Δq|``
        when the state carries a ``q`` plane, and the conflicted-
        constraint count via the generic bucket evaluator
        (observability/metrics.py); solver arithmetic is untouched, so
        telemetry-on selections stay bit-exact."""
        from ..observability.metrics import (conflicts_fn_for,
                                             feature_metrics,
                                             residual_from_q,
                                             write_metric_planes)

        solver = self._solver
        viol_fn = conflicts_fn_for(solver)

        def body(carry):
            s, planes = carry
            s2 = solver.step(s)
            with jax.named_scope("engine/telemetry"):
                i = s["cycle"]
                resid = residual_from_q(s, s2)
                flips = jnp.sum(
                    (solver.assignment_indices(s2)
                     != solver.assignment_indices(s))
                    .astype(jnp.int32))
                viol = viol_fn(solver.assignment_indices(s2)) \
                    .astype(jnp.int32) if viol_fn is not None \
                    else jnp.int32(-1)
                freezes, pruned = feature_metrics(s2)
                planes = write_metric_planes(planes, i, resid, flips,
                                             viol, freezes=freezes,
                                             pruned=pruned)
            return s2, planes

        def run_chunk(carry, limit):
            def cond(c):
                return jnp.logical_and(
                    jnp.logical_not(c[0]["finished"]),
                    c[0]["cycle"] < limit)

            return jax.lax.while_loop(cond, body, carry)

        return run_chunk

    def _metrics_runner(self, carry, limit, spans: bool, clock):
        """The compiled telemetry chunk: plain jit, or the jax.stages
        AOT path when ``spans`` so trace/lower/compile wall times and
        the HLO census are recorded (signature-keyed cache in
        observability/spans.py)."""
        if not spans:
            if self._run_chunk_metrics is None:
                self._run_chunk_metrics = jax.jit(
                    self._metrics_chunk_fn())
            return self._run_chunk_metrics
        from ..observability.spans import aot_cached

        compiled, stats = aot_cached(
            self._aot, "metrics", jax.jit(self._metrics_chunk_fn()),
            (carry, limit), clock)
        self.last_compile_stats = stats
        return compiled

    def run(self, key: int = 0, max_cycles: int = 1000,
            timeout: Optional[float] = None,
            collect_cost_every: Optional[int] = None,
            collect_metrics: bool = False,
            spans: bool = False,
            variables=None,
            checkpointer=None,
            resume: bool = False) -> RunResult:
        """Run until convergence, cycle cap, or wall-clock timeout.
        ``collect_metrics`` records the per-cycle telemetry planes
        (``RunResult.cycle_metrics``); ``spans`` additionally splits
        trace/lower/compile/execute wall time via jax.stages and fills
        ``RunResult.compile_stats``.  The pure-numpy host path has no
        compiled chunk to instrument: small problems keep taking it
        (bit-exactness over observability) and return empty
        telemetry.

        ``checkpointer`` (robustness/checkpoint.SolveCheckpointer)
        snapshots the solver carry — and the telemetry planes when
        collecting — at the loop's EXISTING chunk boundaries (the
        per-boundary two-scalar read is the only host sync either
        way); ``resume`` restores the snapshot (fingerprint- and
        signature-checked, refusing loudly on mismatch) instead of a
        fresh ``init_state``, reproducing the uninterrupted run's
        selections and cycles bit-exactly (boundary-invariant chunk
        arithmetic, the PR 2 guard).  A checkpointed run always takes
        the compiled path: the host mirror has no chunk boundaries to
        snapshot at."""
        from ..observability.metrics import (alloc_metric_planes,
                                             metric_records)
        from ..observability.spans import SpanClock

        solver = self._solver
        if (checkpointer is None
                and getattr(solver, "host_path", False)
                and solver.use_host_engine()
                and solver.host_cells() <= HOST_ENGINE_CELLS):
            return solver.host_run(
                max_cycles=max_cycles, timeout=timeout,
                collect_cost_every=collect_cost_every,
                variables=variables)
        if isinstance(key, int):
            key = jax.random.PRNGKey(key)
        state = self._solver.init_state(key)
        planes = alloc_metric_planes(max_cycles) \
            if collect_metrics else None
        if checkpointer is not None and resume:
            from ..robustness.checkpoint import (tree_to_device,
                                                 tree_to_host)

            template = {"state": tree_to_host(state),
                        "planes": (tree_to_host(planes)
                                   if planes is not None else None)}
            restored = checkpointer.load(template=template)
            if restored is not None:
                state = tree_to_device(restored["state"])
                if planes is not None:
                    planes = tree_to_device(restored["planes"])
        clock = SpanClock()
        t0 = time.perf_counter()
        status = "MAX_CYCLES"
        trace = []
        chunk = (collect_cost_every if collect_cost_every
                 else self._chunk)
        while True:
            cycle = int(state["cycle"])
            if bool(state["finished"]):
                status = "FINISHED"
                break
            if cycle >= max_cycles:
                status = "MAX_CYCLES"
                break
            if timeout is not None and time.perf_counter() - t0 > timeout:
                status = "TIMEOUT"
                break
            if checkpointer is not None and cycle:
                # the boundary the loop head just paid its two-scalar
                # sync for; the snapshot gather happens only when due
                checkpointer.maybe_save(cycle, lambda: {
                    "state": _host_tree(state),
                    "planes": (_host_tree(planes)
                               if planes is not None else None)})
            limit = min(cycle + chunk, max_cycles)
            if collect_metrics:
                run_chunk = self._metrics_runner(
                    (state, planes), jnp.int32(limit), spans, clock)
                state, planes = run_chunk((state, planes),
                                          jnp.int32(limit))
            else:
                state = self._run_chunk(state, jnp.int32(limit))
            if collect_cost_every:
                trace.append(
                    (int(state["cycle"]), float(self._cost(state)))
                )
        if checkpointer is not None:
            # the final boundary (finished, budget, or timeout): a
            # resume replays this snapshot and continues — or, for a
            # finished run, decodes the identical result
            checkpointer.maybe_save(cycle, lambda: {
                "state": _host_tree(state),
                "planes": (_host_tree(planes)
                           if planes is not None else None)},
                final=True)
        duration = time.perf_counter() - t0
        clock.add("execute_s", duration)
        self.last_spans = clock.as_dict() if spans else {}

        idx = jax.device_get(self._idx(state))
        cost = float(self._cost(state))
        assignment = self._named_assignment(idx, variables)
        result = RunResult(
            assignment=assignment,
            cycles=int(state["cycle"]),
            finished=bool(state["finished"]),
            cost=cost,
            violations=0,
            duration=duration,
            status=status,
            cost_trace=trace,
        )
        if collect_metrics:
            result.cycle_metrics = metric_records(
                planes, result.cycles)
            result.compile_stats = dict(self.last_compile_stats)
            if spans:
                result.metrics["spans"] = dict(self.last_spans)
        if checkpointer is not None:
            result.metrics["checkpoint"] = checkpointer.telemetry()
        return result

    def _named_assignment(self, idx, variables):
        if variables is not None:
            by_name = {v.name: v for v in variables}
            return {
                name: by_name[name].domain.values[int(i)]
                for name, i in zip(self._solver.var_names, idx)
            }
        return {
            name: int(i) for name, i in zip(self._solver.var_names, idx)
        }

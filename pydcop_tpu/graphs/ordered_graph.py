"""Ordered chain graph (for SyncBB).

reference parity: pydcop/computations_graph/ordered_graph.py:46-206 —
variables in lexical order, each node linked to the next/previous one.
"""

from typing import Iterable, List, Optional

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import Constraint
from .objects import ComputationGraph, ComputationNode, Link


class OrderLink(Link):
    def __init__(self, link_type: str, source: str, target: str):
        if link_type not in ("next", "previous"):
            raise ValueError(f"Invalid order link type {link_type}")
        super().__init__([source, target], link_type)
        self._source = source
        self._target = target

    @property
    def source(self):
        return self._source

    @property
    def target(self):
        return self._target


class OrderedVarNode(ComputationNode):
    def __init__(self, variable: Variable,
                 constraints: Iterable[Constraint],
                 position: int,
                 previous_node: Optional[str],
                 next_node: Optional[str]):
        links = []
        if previous_node:
            links.append(OrderLink("previous", variable.name, previous_node))
        if next_node:
            links.append(OrderLink("next", variable.name, next_node))
        super().__init__(variable.name, "OrderedVarNode", links)
        self._variable = variable
        self._constraints = list(constraints)
        self._position = position
        self._previous_node = previous_node
        self._next_node = next_node

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        return list(self._constraints)

    @property
    def position(self) -> int:
        return self._position

    @property
    def previous_node(self) -> Optional[str]:
        return self._previous_node

    @property
    def next_node(self) -> Optional[str]:
        return self._next_node


class OrderedGraph(ComputationGraph):
    def __init__(self, nodes: Iterable[OrderedVarNode]):
        nodes = sorted(nodes, key=lambda n: n.position)
        super().__init__("OrderedGraph", nodes)

    @property
    def ordered_nodes(self) -> List[OrderedVarNode]:
        return list(self.nodes)


def build_computation_graph(dcop: Optional[DCOP] = None,
                            variables: Optional[Iterable[Variable]] = None,
                            constraints: Optional[Iterable[Constraint]] = None
                            ) -> OrderedGraph:
    """Chain of variables in lexical name order
    (reference: ordered_graph.py:182-206)."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    ordered = sorted(variables, key=lambda v: v.name)
    names = [v.name for v in ordered]
    nodes = []
    for i, v in enumerate(ordered):
        # constraints whose scope's *last* variable (in the order) is v:
        # handled when the chain token reaches v
        v_constraints = [
            c for c in constraints
            if max(
                (names.index(x.name) for x in c.dimensions
                 if x.name in names),
                default=-1,
            ) == i
        ]
        nodes.append(OrderedVarNode(
            v, v_constraints, i,
            names[i - 1] if i > 0 else None,
            names[i + 1] if i < len(names) - 1 else None,
        ))
    return OrderedGraph(nodes)

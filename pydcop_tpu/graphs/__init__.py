"""Computation-graph builders and their padded-array exports.

Each sub-module exposes ``build_computation_graph(dcop)`` like the
reference's ``pydcop/computations_graph`` package; :mod:`arrays` exports
the compiled on-device form.
"""

from . import constraints_hypergraph, factor_graph, ordered_graph, pseudotree
from .arrays import FactorGraphArrays, HypergraphArrays
from .objects import ComputationGraph, ComputationNode, Link

GRAPH_MODULES = {
    "factor_graph": factor_graph,
    "constraints_hypergraph": constraints_hypergraph,
    "pseudotree": pseudotree,
    "ordered_graph": ordered_graph,
}


def load_graph_module(graph_type: str):
    """Parity with the reference's dynamic graph-module loading
    (pydcop/computations_graph/__init__.py)."""
    try:
        return GRAPH_MODULES[graph_type]
    except KeyError:
        raise ImportError(f"Unknown graph type: {graph_type}")


__all__ = [
    "ComputationGraph", "ComputationNode", "Link",
    "FactorGraphArrays", "HypergraphArrays",
    "factor_graph", "constraints_hypergraph", "pseudotree", "ordered_graph",
    "load_graph_module", "GRAPH_MODULES",
]

"""DFS pseudo-tree computation graph (for DPOP / NCBB).

reference parity: pydcop/computations_graph/pseudotree.py:178-539.  The
reference builds the tree through a token-passing simulation; the result is
a plain DFS tree, so we compute it directly host-side (iterative DFS, no
recursion limit on 10k+ variable problems) with the same heuristics:

* root = highest-degree variable (pseudotree.py:350),
* pseudo-parent/pseudo-child classification from back-edges,
* each constraint is handled by the *lowest* (deepest) node of its scope
  (pseudotree.py:452, ``_filter_relation_to_lowest_node``),
* forests (disconnected problems) yield several roots (pseudotree.py:531).
"""

from typing import Dict, Iterable, List, Optional, Tuple

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import Constraint
from .objects import ComputationGraph, ComputationNode, Link


class PseudoTreeLink(Link):
    def __init__(self, link_type: str, source: str, target: str):
        # link_type: parent | pseudo_parent
        super().__init__([source, target], link_type)
        self._source = source
        self._target = target

    @property
    def source(self):
        return self._source

    @property
    def target(self):
        return self._target


class PseudoTreeNode(ComputationNode):
    def __init__(self, variable: Variable,
                 constraints: Iterable[Constraint],
                 parent: Optional[str] = None,
                 pseudo_parents: Optional[List[str]] = None,
                 children: Optional[List[str]] = None,
                 pseudo_children: Optional[List[str]] = None,
                 depth: int = 0):
        self._parent = parent
        self._pseudo_parents = list(pseudo_parents or [])
        self._children = list(children or [])
        self._pseudo_children = list(pseudo_children or [])
        links = []
        if parent:
            links.append(PseudoTreeLink("parent", variable.name, parent))
        for pp in self._pseudo_parents:
            links.append(PseudoTreeLink("pseudo_parent", variable.name, pp))
        for c in self._children:
            links.append(PseudoTreeLink("children", variable.name, c))
        for pc in self._pseudo_children:
            links.append(PseudoTreeLink("pseudo_children", variable.name, pc))
        super().__init__(variable.name, "PseudoTreeComputation", links)
        self._variable = variable
        self._constraints = list(constraints)
        self._depth = depth

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        """Constraints this node is responsible for (lowest-node rule)."""
        return list(self._constraints)

    @property
    def parent(self) -> Optional[str]:
        return self._parent

    @property
    def pseudo_parents(self) -> List[str]:
        return list(self._pseudo_parents)

    @property
    def children(self) -> List[str]:
        return list(self._children)

    @property
    def pseudo_children(self) -> List[str]:
        return list(self._pseudo_children)

    @property
    def depth(self) -> int:
        return self._depth

    @property
    def is_root(self) -> bool:
        return self._parent is None

    @property
    def is_leaf(self) -> bool:
        return not self._children


class ComputationPseudoTree(ComputationGraph):
    def __init__(self, nodes: Iterable[PseudoTreeNode]):
        super().__init__("PseudoTree", list(nodes))
        self._by_name: Dict[str, PseudoTreeNode] = {
            n.name: n for n in self.nodes
        }

    @property
    def roots(self) -> List[PseudoTreeNode]:
        return [n for n in self.nodes if n.is_root]

    def node(self, name: str) -> PseudoTreeNode:
        return self._by_name[name]

    def depth_ordered(self) -> List[List[PseudoTreeNode]]:
        """Nodes grouped by depth, root level first — the schedule for
        DPOP's level-synchronous UTIL/VALUE sweeps."""
        levels: Dict[int, List[PseudoTreeNode]] = {}
        for n in self.nodes:
            levels.setdefault(n.depth, []).append(n)
        return [levels[d] for d in sorted(levels)]


def _adjacency(variables: List[Variable],
               constraints: List[Constraint]) -> Dict[str, List[str]]:
    adj: Dict[str, set] = {v.name: set() for v in variables}
    for c in constraints:
        names = [v.name for v in c.dimensions if v.name in adj]
        for i, n1 in enumerate(names):
            for n2 in names[i + 1:]:
                if n1 != n2:
                    adj[n1].add(n2)
                    adj[n2].add(n1)
    # deterministic neighbor order: degree desc, then name
    return {
        n: sorted(neigh, key=lambda m: (-len(adj[m]), m))
        for n, neigh in adj.items()
    }


def build_computation_graph(dcop: Optional[DCOP] = None,
                            variables: Optional[Iterable[Variable]] = None,
                            constraints: Optional[Iterable[Constraint]] = None
                            ) -> ComputationPseudoTree:
    """Build a DFS pseudo-tree (reference: pseudotree.py:472-539)."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    adj = _adjacency(variables, constraints)
    var_by_name = {v.name: v for v in variables}

    visited: Dict[str, int] = {}  # name -> depth
    parent: Dict[str, Optional[str]] = {}
    children: Dict[str, List[str]] = {n: [] for n in adj}
    pseudo_parents: Dict[str, List[str]] = {n: [] for n in adj}
    pseudo_children: Dict[str, List[str]] = {n: [] for n in adj}

    unvisited = set(adj)
    while unvisited:
        # root of this tree: max degree (ties by name) — pseudotree.py:350
        root = max(sorted(unvisited), key=lambda n: len(adj[n]))
        # iterative DFS; on_path tracks the current root-path for back-edge
        # classification
        on_path: Dict[str, int] = {}
        # we emulate recursion with an explicit enter/exit stack
        work: List[Tuple[str, Optional[str], int, bool]] = [
            (root, None, 0, False)
        ]
        while work:
            node, par, depth, done = work.pop()
            if done:
                on_path.pop(node, None)
                continue
            if node in visited:
                continue
            visited[node] = depth
            parent[node] = par
            if par is not None:
                children[par].append(node)
            on_path[node] = depth
            work.append((node, par, depth, True))
            # push children in reverse so the first neighbor is explored
            # first
            for m in reversed(adj[node]):
                if m not in visited:
                    work.append((m, node, depth + 1, False))
                elif m in on_path and m != par:
                    # back-edge: m is an ancestor of node
                    if m not in pseudo_parents[node]:
                        pseudo_parents[node].append(m)
                        pseudo_children[m].append(node)
        unvisited -= set(visited) & unvisited

    # lowest-node rule: a constraint is handled by the deepest variable of
    # its scope (ties broken by name for determinism)
    constraints_of: Dict[str, List[Constraint]] = {n: [] for n in adj}
    for c in constraints:
        names = [v.name for v in c.dimensions if v.name in visited]
        if not names:
            continue
        lowest = max(names, key=lambda n: (visited[n], n))
        constraints_of[lowest].append(c)

    nodes = [
        PseudoTreeNode(
            var_by_name[n],
            constraints_of[n],
            parent=parent[n],
            pseudo_parents=pseudo_parents[n],
            children=children[n],
            pseudo_children=pseudo_children[n],
            depth=visited[n],
        )
        for n in adj
    ]
    return ComputationPseudoTree(nodes)


def get_dfs_relations(node: PseudoTreeNode):
    """Split a node's constraints by whether they involve ancestors
    (reference: pseudotree.py:178-241)."""
    ancestors = set(node.pseudo_parents)
    if node.parent:
        ancestors.add(node.parent)
    with_ancestors, own = [], []
    for c in node.constraints:
        if any(v.name in ancestors for v in c.dimensions):
            with_ancestors.append(c)
        else:
            own.append(c)
    return with_ancestors, own

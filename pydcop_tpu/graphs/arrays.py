"""Padded array export of computation graphs — the on-device representation.

This is the load-bearing design decision of the TPU framework (SURVEY.md §7):
the computation graph is compiled once, host-side, into dense padded index
arrays; one algorithm round over the *whole* graph is then a single jitted
XLA program of gathers, broadcast-adds and segment reductions.  Message
delivery — the reference's entire infrastructure layer of queues, threads
and HTTP posts (pydcop/infrastructure/communication.py) — becomes array
indexing on-chip.

Conventions
-----------
* Variables and factors are integer ids in model iteration order.
* All domains are padded to ``max_domain``; invalid slots are masked and
  carry ``BIG`` cost so no reduction ever selects them.
* ``max`` objectives are compiled to ``min`` by negating every cost at
  build time (``sign``); reported costs are re-evaluated host-side.
* Factors/constraints are bucketed by arity; each bucket stacks its cost
  hypercubes into one ``(n, D, ..., D)`` tensor — static shapes, ready for
  the MXU/VPU.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..dcop.dcop import DCOP
from ..dcop.relations import Constraint

BIG = np.float32(1e9)
# Hard-constraint costs (inf in the model) are clipped to this so sums of a
# few violations stay well under BIG and far from float32 overflow.
HARD = np.float32(1e7)
# The masking sentinel every masked min/argmin substitutes for invalid
# slots (ops/kernels.py masked_argmin / masked_min / random_argmin and
# the solvers' inlined selections).  Strictly above BIG so a masked slot
# can never tie a BIG-padded (but valid-shaped) entry, and chosen to
# survive bf16 rounding with the ordering intact: the precision layer
# (ops/precision.py) stores cost planes in bfloat16, whose 8 significand
# bits round both constants, so SENTINEL > BIG must hold AFTER rounding
# too — asserted at import below, not assumed.
SENTINEL = np.float32(2e9)

try:
    from ml_dtypes import bfloat16 as _bf16

    assert float(_bf16(SENTINEL)) > float(_bf16(BIG)) > float(
        _bf16(HARD)) > 0.0, (
        "masking sentinels must stay strictly ordered after bf16 "
        "rounding (SENTINEL > BIG > HARD); adjust the constants")
    del _bf16
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    pass


class CostPlaneError(ValueError):
    """A cost table that would silently corrupt the compiled planes.

    NaN is the poison this guards: ``_clip_costs`` would launder it to
    cost 0 (``nan_to_num``), after which every min-sum reduction on
    device happily optimizes a model the user never wrote — invisible
    until someone audits an answer.  ``±inf`` is NOT rejected: it is
    the documented hard-constraint encoding, clipped to ``±HARD`` at
    build time (``--infinity`` at-or-above semantics).  ``kind`` is
    ``"constraint"`` or ``"variable"`` and ``name`` the offending
    model element, so serve admission can surface a structured
    ``REJECTED`` reason naming it."""

    def __init__(self, kind: str, name: str, nan_count: int):
        super().__init__(
            f"{kind} {name!r} carries {nan_count} NaN cost "
            f"value(s); NaN would silently become cost 0 in the "
            f"compiled planes and poison min-sum reductions — use "
            f"inf for hard constraints, finite costs otherwise")
        self.kind = kind
        self.name = name
        self.nan_count = int(nan_count)


def _require_no_nan(raw: np.ndarray, kind: str, name: str):
    """Loud build-time gate on raw cost input (before the sign/clip
    laundering); raises :class:`CostPlaneError` naming the model
    element."""
    nan = int(np.isnan(np.asarray(raw, dtype=np.float32)).sum())
    if nan:
        raise CostPlaneError(kind, name, nan)


def _clip_costs(cube: np.ndarray, sign: float) -> np.ndarray:
    cube = np.asarray(cube, dtype=np.float32) * np.float32(sign)
    cube = np.nan_to_num(cube, posinf=HARD, neginf=-HARD)
    return np.clip(cube, -HARD, HARD)


def _padded_cube(constraint: Constraint, max_domain: int,
                 sign: float) -> np.ndarray:
    raw = constraint.cost_hypercube()
    _require_no_nan(raw, "constraint", constraint.name)
    cube = _clip_costs(raw, sign)
    pads = [(0, max_domain - s) for s in cube.shape]
    return np.pad(cube, pads, constant_values=BIG)


def _pad_var_plane(arrays, n_vars: int):
    """Shared variable-plane padding for ``pad_to``: phantom variables
    occupy rows ``[arrays.n_vars, n_vars)`` with a single valid domain
    slot of cost 0, so they can never influence a reduction over real
    variables and always select index 0.  Returns the padded
    ``(var_names, domain_size, domain_mask, var_costs, var_valid)``."""
    V, D = arrays.n_vars, arrays.max_domain
    pad = n_vars - V
    var_names = list(arrays.var_names) + [f"__pad{i}" for i in range(pad)]
    domain_size = np.concatenate(
        [arrays.domain_size, np.ones(pad, dtype=np.int32)])
    pad_mask = np.zeros((pad, D), dtype=bool)
    pad_mask[:, 0] = True
    domain_mask = np.concatenate([arrays.domain_mask, pad_mask])
    # dtype-preserving: a bf16-stored instance pads with bf16 phantoms
    pad_costs = np.full((pad, D), BIG, dtype=arrays.var_costs.dtype)
    pad_costs[:, 0] = 0.0
    var_costs = np.concatenate([arrays.var_costs, pad_costs])
    var_valid = np.arange(n_vars) < V
    return var_names, domain_size, domain_mask, var_costs, var_valid


def _phantom_cube(arity: int, max_domain: int,
                  dtype=np.float32) -> np.ndarray:
    """The phantom factor's identity cost cube: 0 at the all-zero
    assignment (the only valid assignment of phantom variables, whose
    domains are the single slot 0) and BIG elsewhere — the same padded
    form a real domain-1 constraint compiles to."""
    cube = np.full((max_domain,) * arity, BIG, dtype=dtype)
    cube[(0,) * arity] = 0.0
    return cube


def _apply_reserve(bucket_slots: Dict[int, int],
                   reserve: Optional[Dict[int, int]]) -> Dict[int, int]:
    """Fold an explicit headroom reservation into the pad targets:
    ``reserve[arity]`` EXTRA phantom slots beyond whatever the ladder
    (or the caller) already asked for — including arities the instance
    has no factors of yet, which is exactly how a dynamic workload
    provisions capacity for constraints a scenario will add later
    (``dynamics/``).  Negative reservations are a caller bug."""
    out = dict(bucket_slots)
    for arity, extra in (reserve or {}).items():
        arity, extra = int(arity), int(extra)
        if arity < 1 or extra < 0:
            raise ValueError(
                f"reserve wants {{arity >= 1: extra slots >= 0}}, got "
                f"{{{arity}: {extra}}}")
        out[arity] = out.get(arity, 0) + extra
    return out


def _check_pad_targets(arrays, n_vars: int, bucket_slots):
    counts = {b.arity: len(b.cons_ids) if hasattr(b, "cons_ids")
              else len(b.factor_ids) for b in arrays.buckets}
    if n_vars < arrays.n_vars:
        raise ValueError(
            f"pad_to target n_vars={n_vars} below instance "
            f"n_vars={arrays.n_vars}")
    for arity, have in counts.items():
        if bucket_slots.get(arity, 0) < have:
            raise ValueError(
                f"pad_to target {bucket_slots.get(arity, 0)} slots for "
                f"arity {arity} below instance count {have}")
    needs_phantom = any(
        bucket_slots[a] > counts.get(a, 0) for a in bucket_slots)
    if needs_phantom and n_vars == arrays.n_vars:
        raise ValueError(
            "padding in phantom factors needs at least one phantom "
            "variable to anchor them: pass n_vars > instance n_vars")


def _apply_precision(arrays, precision):
    """Cast the cost planes (cubes + unary variable costs) of freshly
    built arrays to the policy's ``store_dtype``
    (``ops/precision.py``).  Index tables, masks and sizes stay
    integer/bool; ``None`` keeps the f32 default so every existing
    caller is untouched.  bf16 storage is exact for integer costs with
    ``|cost| <= 256`` — the built-in generators — and the BIG padding
    constant rounds monotonically (SENTINEL > bf16(BIG) asserted
    above), so masked slots keep dominating every reduction."""
    if precision is None:
        return arrays
    from ..ops.precision import resolve, store

    policy = resolve(precision)
    arrays.var_costs = store(arrays.var_costs, policy)
    for b in arrays.buckets:
        b.cubes = store(b.cubes, policy)
    return arrays


def _bind_externals(dcop: Optional[DCOP], constraints: list) -> list:
    """External (sensor) variables are not decision variables: fix them at
    their current value by slicing the constraints at compile time.  The
    host re-compiles when an external value changes (the dynamic-DCOP
    path), keeping the on-device problem purely over decision variables."""
    ext = dcop.external_variables if dcop is not None else {}
    if not ext:
        return constraints
    out = []
    for c in constraints:
        fixed = {
            v.name: ext[v.name].value
            for v in c.dimensions if v.name in ext
        }
        out.append(c.slice(fixed) if fixed else c)
    return out


@dataclass
class FactorBucket:
    """All factors of one arity, stacked."""

    arity: int
    factor_ids: np.ndarray          # (Fa,) global factor index
    cubes: np.ndarray               # (Fa, D, ..., D) padded costs
    edge_ids: np.ndarray            # (Fa, arity) edge index per position
    var_ids: np.ndarray             # (Fa, arity) variable index per position

    def cubes_lane_major(self) -> np.ndarray:
        """The lane-major view of the stacked hypercubes: factor axis
        LAST (``(D, ..., D, Fa)``), so factors ride the 128-wide lane
        dimension and the small domain axes live in sublanes — the
        layout the fused factor kernels consume
        (``ops/pallas_kernels.py``)."""
        return np.moveaxis(self.cubes, 0, -1)


def canonical_edge_layout(arrays: "FactorGraphArrays"):
    """Per-bucket ``(edge_offset, n_factors, arity)`` specs when the
    edge layout is canonical factor-major — bucket blocks are
    contiguous and edges ``a*i .. a*i+arity-1`` of a block are factor
    ``i``'s positions in order — else ``None``.

    Canonical layout turns every per-bucket edge gather/scatter of the
    message-passing cycle into a static slice + reshape; the fast
    generators emit it directly and :meth:`FactorGraphArrays.build`
    produces it for any model when given ``arity_sorted=True``.
    Arity-0 buckets (constants) get a ``None`` spec entry.
    """
    offset = 0
    layout = []
    for b in arrays.buckets:
        arity = b.cubes.ndim - 1
        if arity == 0:
            layout.append(None)
            continue
        f = b.edge_ids.shape[0]
        expected = offset + np.arange(f * arity, dtype=np.int64) \
            .reshape(f, arity)
        if not np.array_equal(np.asarray(b.edge_ids), expected):
            return None
        layout.append((offset, f, arity))
        offset += f * arity
    if offset != arrays.n_edges:
        return None
    return layout


@dataclass
class FactorGraphArrays:
    """Compiled factor graph for the max-sum family."""

    n_vars: int
    n_factors: int
    n_edges: int
    max_domain: int
    sign: float                      # +1 min, -1 max
    var_names: List[str]
    factor_names: List[str]
    domain_size: np.ndarray          # (V,)
    domain_mask: np.ndarray          # (V, D) bool
    var_costs: np.ndarray            # (V, D) unary costs, BIG-padded
    edge_var: np.ndarray             # (E,)
    edge_factor: np.ndarray          # (E,)
    buckets: List[FactorBucket] = field(default_factory=list)
    # set by pad_to: the instance's true variable count and a (V,) bool
    # mask of real (non-phantom) variable rows
    n_vars_true: Optional[int] = None
    var_valid: Optional[np.ndarray] = None

    @classmethod
    def build(cls, dcop: DCOP,
              variables=None, constraints=None,
              arity_sorted: bool = False,
              precision=None) -> "FactorGraphArrays":
        if variables is None:
            variables = list(dcop.variables.values())
        if constraints is None:
            constraints = list(dcop.constraints.values())
        constraints = _bind_externals(dcop, constraints)
        if arity_sorted:
            # stable arity sort makes every bucket's edge block
            # contiguous, i.e. the canonical factor-major layout the
            # lane/fused solvers need (see canonical_edge_layout) —
            # for ANY model, not just single-arity generator output
            constraints = sorted(constraints, key=lambda c: c.arity)
        sign = 1.0 if dcop.objective == "min" else -1.0

        var_names = [v.name for v in variables]
        var_idx = {n: i for i, n in enumerate(var_names)}
        factor_names = [c.name for c in constraints]
        V, F = len(variables), len(constraints)
        D = max((len(v.domain) for v in variables), default=1)

        domain_size = np.array([len(v.domain) for v in variables],
                               dtype=np.int32)
        domain_mask = np.arange(D)[None, :] < domain_size[:, None]
        var_costs = np.full((V, D), BIG, dtype=np.float32)
        for i, v in enumerate(variables):
            raw = np.array([v.cost_for_val(val) for val in v.domain])
            _require_no_nan(raw, "variable", v.name)
            var_costs[i, : len(v.domain)] = _clip_costs(raw, sign)

        edge_var, edge_factor = [], []
        by_arity: Dict[int, List[int]] = {}
        edge_of: Dict[Tuple[int, int], int] = {}
        for f, c in enumerate(constraints):
            by_arity.setdefault(c.arity, []).append(f)
            for p, v in enumerate(c.dimensions):
                edge_of[(f, p)] = len(edge_var)
                edge_var.append(var_idx[v.name])
                edge_factor.append(f)
        E = len(edge_var)

        buckets = []
        for arity in sorted(by_arity):
            ids = by_arity[arity]
            cubes = np.stack([
                _padded_cube(constraints[f], D, sign) for f in ids
            ])
            e_ids = np.array(
                [[edge_of[(f, p)] for p in range(arity)] for f in ids],
                dtype=np.int32,
            )
            v_ids = np.array(
                [[var_idx[constraints[f].dimensions[p].name]
                  for p in range(arity)] for f in ids],
                dtype=np.int32,
            )
            buckets.append(FactorBucket(
                arity, np.array(ids, dtype=np.int32), cubes, e_ids, v_ids))

        out = cls(
            n_vars=V, n_factors=F, n_edges=E, max_domain=D, sign=sign,
            var_names=var_names, factor_names=factor_names,
            domain_size=domain_size, domain_mask=domain_mask,
            var_costs=var_costs,
            edge_var=np.array(edge_var, dtype=np.int32),
            edge_factor=np.array(edge_factor, dtype=np.int32),
            buckets=buckets,
        )
        return _apply_precision(out, precision)

    def assignment_from_indices(self, idx: np.ndarray,
                                variables) -> Dict[str, object]:
        return {
            v.name: v.domain.values[int(i)]
            for v, i in zip(variables, idx)
        }

    def pad_to(self, n_vars: int,
               bucket_slots: Dict[int, int],
               reserve: Optional[Dict[int, int]] = None
               ) -> "FactorGraphArrays":
        """Pad this instance to a canonical shared shape so instances
        with different V/E/arity profiles fuse into ONE vmapped program
        (parallel/bucketing.py picks the targets).  ``reserve`` adds
        EXPLICIT headroom on top: ``{arity: extra slots}`` phantom
        factor slots beyond ``bucket_slots`` (new arities allowed), the
        edit capacity dynamic workloads activate in place
        (``dynamics/deltas.py``) — variable headroom travels through a
        larger ``n_vars``.

        Phantom variables (rows ``[self.n_vars, n_vars)``) have a single
        valid domain slot of cost 0 and are masked out of every
        selection and cost; phantom factors carry the identity cost
        cube of that slot and anchor ALL their positions on the last
        phantom variable, so no phantom quantity ever reaches a real
        variable's messages, beliefs, or convergence delta.  Edges are
        renumbered into the canonical factor-major layout over the
        padded buckets (real factors keep their relative order inside
        each arity bucket), so every instance padded to the same
        targets shares one index structure and the fast slice/reshape
        paths stay available.  The result records ``n_vars_true`` and a
        ``var_valid`` mask for the masked decode."""
        bucket_slots = _apply_reserve(bucket_slots, reserve)
        _check_pad_targets(self, n_vars, bucket_slots)
        D = self.max_domain
        var_names, domain_size, domain_mask, var_costs, var_valid = \
            _pad_var_plane(self, n_vars)
        sink = n_vars - 1

        by_arity = {b.cubes.ndim - 1: b for b in self.buckets}
        factor_names: List[str] = []
        buckets, edge_var, edge_factor = [], [], []
        n_factors = 0
        for arity in sorted(bucket_slots):
            slots = bucket_slots[arity]
            if slots == 0:
                continue
            b = by_arity.get(arity)
            have = len(b.factor_ids) if b is not None else 0
            pad = slots - have
            cubes = [np.asarray(b.cubes)] if b is not None else []
            v_ids = [np.asarray(b.var_ids)] if b is not None else []
            if b is not None:
                factor_names += [self.factor_names[f]
                                 for f in b.factor_ids]
            if pad:
                cubes.append(np.broadcast_to(
                    _phantom_cube(arity, D,
                                  dtype=self.var_costs.dtype),
                    (pad,) + (D,) * arity))
                v_ids.append(np.full((pad, arity), sink,
                                     dtype=np.int32))
                factor_names += [f"__padf{arity}_{i}"
                                 for i in range(pad)]
            cubes = np.concatenate(cubes) if len(cubes) > 1 \
                else cubes[0]
            v_ids = np.concatenate(v_ids) if len(v_ids) > 1 \
                else v_ids[0]
            f_ids = n_factors + np.arange(slots, dtype=np.int32)
            e_ids = (len(edge_var)
                     + np.arange(slots * arity, dtype=np.int32)
                     .reshape(slots, arity)) if arity else \
                np.zeros((slots, 0), dtype=np.int32)
            edge_var.extend(v_ids.reshape(-1).tolist())
            edge_factor.extend(np.repeat(f_ids, arity).tolist())
            n_factors += slots
            buckets.append(FactorBucket(
                arity, f_ids, np.ascontiguousarray(cubes), e_ids,
                np.ascontiguousarray(v_ids)))

        return FactorGraphArrays(
            n_vars=n_vars, n_factors=n_factors, n_edges=len(edge_var),
            max_domain=D, sign=self.sign, var_names=var_names,
            factor_names=factor_names, domain_size=domain_size,
            domain_mask=domain_mask, var_costs=var_costs,
            edge_var=np.array(edge_var, dtype=np.int32),
            edge_factor=np.array(edge_factor, dtype=np.int32),
            buckets=buckets,
            n_vars_true=self.n_vars, var_valid=var_valid,
        )


@dataclass
class ConstraintBucket:
    """All constraints of one arity, stacked (hypergraph form)."""

    arity: int
    cons_ids: np.ndarray            # (Ca,)
    cubes: np.ndarray               # (Ca, D, ..., D)
    var_ids: np.ndarray             # (Ca, arity)


@dataclass
class HypergraphArrays:
    """Compiled constraints hypergraph for local-search algorithms."""

    n_vars: int
    n_constraints: int
    max_domain: int
    sign: float
    var_names: List[str]
    domain_size: np.ndarray          # (V,)
    domain_mask: np.ndarray          # (V, D)
    var_costs: np.ndarray            # (V, D)
    initial_idx: np.ndarray          # (V,) initial value indices
    has_initial: np.ndarray          # (V,) bool: explicit initial value?
    buckets: List[ConstraintBucket] = field(default_factory=list)
    # variable-to-variable neighbor pairs (deduped, both directions),
    # for gain-exchange style algorithms (mgm, dba ...)
    nbr_src: np.ndarray = None       # (P,)
    nbr_dst: np.ndarray = None       # (P,)
    max_degree: int = 0              # max #neighbors of any variable
    max_arity_minus_one: int = 0     # for DSA p_mode thresholds
    # set by pad_to: the instance's true variable count and a (V,) bool
    # mask of real (non-phantom) variable rows
    n_vars_true: Optional[int] = None
    var_valid: Optional[np.ndarray] = None

    @classmethod
    def build(cls, dcop: DCOP,
              variables=None, constraints=None,
              precision=None) -> "HypergraphArrays":
        if variables is None:
            variables = list(dcop.variables.values())
        if constraints is None:
            constraints = list(dcop.constraints.values())
        constraints = _bind_externals(dcop, constraints)
        sign = 1.0 if dcop.objective == "min" else -1.0

        var_names = [v.name for v in variables]
        var_idx = {n: i for i, n in enumerate(var_names)}
        V = len(variables)
        D = max((len(v.domain) for v in variables), default=1)

        domain_size = np.array([len(v.domain) for v in variables],
                               dtype=np.int32)
        domain_mask = np.arange(D)[None, :] < domain_size[:, None]
        var_costs = np.full((V, D), BIG, dtype=np.float32)
        initial_idx = np.zeros(V, dtype=np.int32)
        has_initial = np.zeros(V, dtype=bool)
        for i, v in enumerate(variables):
            raw = np.array([v.cost_for_val(val) for val in v.domain])
            _require_no_nan(raw, "variable", v.name)
            var_costs[i, : len(v.domain)] = _clip_costs(raw, sign)
            if v.initial_value is not None:
                initial_idx[i] = v.domain.index(v.initial_value)
                has_initial[i] = True

        by_arity: Dict[int, List[int]] = {}
        for ci, c in enumerate(constraints):
            by_arity.setdefault(c.arity, []).append(ci)

        buckets = []
        pairs = set()
        for arity in sorted(by_arity):
            ids = by_arity[arity]
            cubes = np.stack([
                _padded_cube(constraints[ci], D, sign) for ci in ids
            ])
            v_ids = np.array(
                [[var_idx[v.name] for v in constraints[ci].dimensions]
                 for ci in ids],
                dtype=np.int32,
            )
            buckets.append(ConstraintBucket(
                arity, np.array(ids, dtype=np.int32), cubes, v_ids))
            for ci in ids:
                scope = [var_idx[v.name] for v in constraints[ci].dimensions]
                for i, a in enumerate(scope):
                    for b in scope[i + 1:]:
                        if a != b:
                            pairs.add((a, b))
                            pairs.add((b, a))

        if pairs:
            src, dst = zip(*sorted(pairs))
        else:
            src, dst = (), ()
        degree = np.zeros(V, dtype=np.int64)
        for s in src:
            degree[s] += 1
        max_arity = max((c.arity for c in constraints), default=1)

        out = cls(
            n_vars=V, n_constraints=len(constraints), max_domain=D,
            sign=sign, var_names=var_names,
            domain_size=domain_size, domain_mask=domain_mask,
            var_costs=var_costs, initial_idx=initial_idx,
            has_initial=has_initial, buckets=buckets,
            nbr_src=np.array(src, dtype=np.int32),
            nbr_dst=np.array(dst, dtype=np.int32),
            max_degree=int(degree.max()) if V else 0,
            max_arity_minus_one=max(0, max_arity - 1),
        )
        return _apply_precision(out, precision)

    def pad_to(self, n_vars: int, bucket_slots: Dict[int, int],
               n_pairs: Optional[int] = None,
               reserve: Optional[Dict[int, int]] = None
               ) -> "HypergraphArrays":
        """Hypergraph twin of :meth:`FactorGraphArrays.pad_to`: pad to
        the shared shape a bucket rung prescribes.  Phantom variables
        carry a declared initial value of slot 0 (their only valid
        slot), phantom constraints anchor every position on the last
        phantom variable with the identity cost cube (optimum == cost
        == 0, so they never read as violated), and the neighbor-pair
        edge list is padded with inert ``(sink, sink)`` pairs to
        ``n_pairs`` so gain-exchange reductions keep one static shape
        per rung.  ``reserve`` adds explicit per-arity slot headroom,
        same contract as the factor-graph twin."""
        bucket_slots = _apply_reserve(bucket_slots, reserve)
        _check_pad_targets(self, n_vars, bucket_slots)
        D = self.max_domain
        var_names, domain_size, domain_mask, var_costs, var_valid = \
            _pad_var_plane(self, n_vars)
        pad_v = n_vars - self.n_vars
        initial_idx = np.concatenate(
            [self.initial_idx, np.zeros(pad_v, dtype=np.int32)])
        has_initial = np.concatenate(
            [self.has_initial, np.ones(pad_v, dtype=bool)])
        sink = n_vars - 1

        by_arity = {b.cubes.ndim - 1: b for b in self.buckets}
        buckets = []
        n_cons = 0
        for arity in sorted(bucket_slots):
            slots = bucket_slots[arity]
            if slots == 0:
                continue
            b = by_arity.get(arity)
            have = len(b.cons_ids) if b is not None else 0
            pad = slots - have
            cubes = [np.asarray(b.cubes)] if b is not None else []
            v_ids = [np.asarray(b.var_ids)] if b is not None else []
            if pad:
                cubes.append(np.broadcast_to(
                    _phantom_cube(arity, D,
                                  dtype=self.var_costs.dtype),
                    (pad,) + (D,) * arity))
                v_ids.append(np.full((pad, arity), sink,
                                     dtype=np.int32))
            cubes = np.concatenate(cubes) if len(cubes) > 1 \
                else cubes[0]
            v_ids = np.concatenate(v_ids) if len(v_ids) > 1 \
                else v_ids[0]
            buckets.append(ConstraintBucket(
                arity,
                n_cons + np.arange(slots, dtype=np.int32),
                np.ascontiguousarray(cubes),
                np.ascontiguousarray(v_ids)))
            n_cons += slots

        P = len(self.nbr_src)
        if n_pairs is None:
            n_pairs = P
        if n_pairs < P:
            raise ValueError(
                f"pad_to target n_pairs={n_pairs} below instance "
                f"pair count {P}")
        if n_pairs > P and n_vars == self.n_vars:
            # padding pairs must self-loop on a PHANTOM sink: anchored
            # on a real variable they would feed that variable's own
            # gain/priority back into its neighbor-max and freeze it
            raise ValueError(
                "padding in neighbor pairs needs a phantom sink "
                "variable to anchor them: pass n_vars > instance "
                "n_vars")
        pad_p = n_pairs - P
        nbr_src = np.concatenate(
            [self.nbr_src,
             np.full(pad_p, sink, dtype=np.int32)])
        nbr_dst = np.concatenate(
            [self.nbr_dst,
             np.full(pad_p, sink, dtype=np.int32)])
        degree = np.bincount(nbr_src, minlength=n_vars) \
            if len(nbr_src) else np.zeros(n_vars, dtype=np.int64)

        return HypergraphArrays(
            n_vars=n_vars, n_constraints=n_cons, max_domain=D,
            sign=self.sign, var_names=var_names,
            domain_size=domain_size, domain_mask=domain_mask,
            var_costs=var_costs, initial_idx=initial_idx,
            has_initial=has_initial, buckets=buckets,
            nbr_src=nbr_src, nbr_dst=nbr_dst,
            max_degree=int(degree.max()) if n_vars else 0,
            max_arity_minus_one=max(
                self.max_arity_minus_one,
                max((a - 1 for a in bucket_slots if bucket_slots[a]),
                    default=0)),
            n_vars_true=self.n_vars, var_valid=var_valid,
        )


# --------------------------------------------------------------------- pairs
# Host-side pair-edge table builders shared by the MGM-2 solvers (single
# chip and sharded).  The directed neighbor-pair edge list (nbr_src,
# nbr_dst) is the decision plane of coordinated-move algorithms; these
# compile the per-constraint position pairs onto it with vectorized
# searchsorted lookups instead of per-constraint Python loops.


def pair_edge_lookup(src: np.ndarray, dst: np.ndarray, n_vars: int):
    """Vectorized ``(u, v) -> directed pair-edge id`` lookup.

    Returns a callable mapping int arrays ``u``, ``v`` (any shape) to the
    edge id of ``(u, v)`` in the ``(src, dst)`` list, or 0 where the pair
    is not an edge (callers make slot 0 inert, e.g. by summing all-zero
    dummy contributions into it).
    """
    keys = (np.asarray(src, dtype=np.int64) * (n_vars + 1)
            + np.asarray(dst, dtype=np.int64))
    order = np.argsort(keys).astype(np.int64)
    skeys = keys[order]

    def lookup(u, v):
        u = np.asarray(u, dtype=np.int64)
        v = np.asarray(v, dtype=np.int64)
        k = u * (n_vars + 1) + v
        if len(skeys) == 0:
            return np.zeros(k.shape, dtype=np.int32)
        pos = np.clip(np.searchsorted(skeys, k), 0, len(skeys) - 1)
        found = skeys[pos] == k
        return np.where(found, order[pos], 0).astype(np.int32)

    return lookup


def pair_eids_for_bucket(lookup, var_ids: np.ndarray) -> np.ndarray:
    """``(..., arity)`` var ids -> ``(..., arity, arity)`` pair-edge ids
    (0 on the diagonal and for absent pairs)."""
    a = var_ids.shape[-1]
    m = lookup(var_ids[..., :, None], var_ids[..., None, :])
    m[..., np.eye(a, dtype=bool)] = 0
    return m


def out_edge_table(src: np.ndarray, n_vars: int):
    """Padded per-variable out-edge lists for random partner choice:
    ``((n_vars, max_degree) edge ids, (n_vars,) out-degrees)``."""
    src = np.asarray(src, dtype=np.int64)
    deg = np.bincount(src, minlength=n_vars) if len(src) \
        else np.zeros(n_vars, dtype=np.int64)
    maxdeg = max(1, int(deg.max()) if len(deg) else 1)
    out_edges = np.zeros((n_vars, maxdeg), dtype=np.int32)
    if len(src):
        order = np.argsort(src, kind="stable")
        slot = np.arange(len(src)) - np.searchsorted(src[order], src[order])
        out_edges[src[order], slot] = order.astype(np.int32)
    return out_edges, deg.astype(np.int32)

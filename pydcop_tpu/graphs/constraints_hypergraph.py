"""Constraints hypergraph: one node per variable, one hyperedge per
constraint.

reference parity: pydcop/computations_graph/constraints_hypergraph.py:46-237.
Used by all local-search algorithms (dsa, mgm, mgm2, dba, gdba, ...).
"""

from typing import Iterable, List, Optional

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import Constraint
from .objects import ComputationGraph, ComputationNode, Link


class ConstraintLink(Link):
    """Hyperedge: links every variable in a constraint's scope."""

    def __init__(self, constraint_name: str, nodes: Iterable[str]):
        super().__init__(nodes, "constraint_link")
        self._constraint_name = constraint_name

    @property
    def constraint_name(self) -> str:
        return self._constraint_name

    def __eq__(self, o):
        return (
            isinstance(o, ConstraintLink)
            and self._constraint_name == o._constraint_name
            and self.nodes == o.nodes
        )

    def __hash__(self):
        return hash((self._constraint_name, self.nodes))

    def __repr__(self):
        return f"ConstraintLink({self._constraint_name}, {self.nodes})"


class VariableComputationNode(ComputationNode):
    def __init__(self, variable: Variable,
                 constraints: Iterable[Constraint]):
        self._constraints = list(constraints)
        links = [
            ConstraintLink(c.name, [v.name for v in c.dimensions])
            for c in self._constraints
        ]
        super().__init__(variable.name, "VariableComputation", links)
        self._variable = variable

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        return list(self._constraints)

    def __eq__(self, o):
        return (
            isinstance(o, VariableComputationNode)
            and self._variable == o._variable
        )

    def __hash__(self):
        return hash(("chg.VariableComputationNode", self._name))


class ComputationConstraintsHyperGraph(ComputationGraph):
    def __init__(self, nodes):
        super().__init__("ConstraintHyperGraph", nodes)


def build_computation_graph(dcop: Optional[DCOP] = None,
                            variables: Optional[Iterable[Variable]] = None,
                            constraints: Optional[Iterable[Constraint]] = None
                            ) -> ComputationConstraintsHyperGraph:
    """Build the hypergraph (reference: constraints_hypergraph.py:176-237)."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    nodes = []
    for v in variables:
        v_constraints = [c for c in constraints if v in c.dimensions]
        nodes.append(VariableComputationNode(v, v_constraints))
    return ComputationConstraintsHyperGraph(nodes)

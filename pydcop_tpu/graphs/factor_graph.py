"""Bipartite factor graph: one node per variable, one per constraint.

reference parity: pydcop/computations_graph/factor_graph.py:45-288.
Used by the max-sum family.
"""

from typing import Iterable, List, Optional

from ..dcop.dcop import DCOP
from ..dcop.objects import Variable
from ..dcop.relations import Constraint
from .objects import ComputationGraph, ComputationNode, Link

GRAPH_NODE_TYPE_VARIABLE = "VariableComputation"
GRAPH_NODE_TYPE_FACTOR = "FactorComputation"


class VariableComputationNode(ComputationNode):
    def __init__(self, variable: Variable, factor_names: Iterable[str]):
        factor_names = list(factor_names)
        links = [
            FactorGraphLink(variable.name, f) for f in factor_names
        ]
        super().__init__(variable.name, GRAPH_NODE_TYPE_VARIABLE, links)
        self._variable = variable
        self._factor_names = list(factor_names)

    @property
    def factor_names(self) -> List[str]:
        return list(self._factor_names)

    @property
    def variable(self) -> Variable:
        return self._variable

    def __eq__(self, o):
        return (
            isinstance(o, VariableComputationNode)
            and self._variable == o._variable
        )

    def __hash__(self):
        return hash(("VariableComputationNode", self._name))


class FactorComputationNode(ComputationNode):
    def __init__(self, factor: Constraint, name: Optional[str] = None):
        name = name if name else factor.name
        links = [FactorGraphLink(name, v.name) for v in factor.dimensions]
        super().__init__(name, GRAPH_NODE_TYPE_FACTOR, links)
        self._factor = factor

    @property
    def factor(self) -> Constraint:
        return self._factor

    @property
    def variables(self) -> List[Variable]:
        return self._factor.dimensions

    def __eq__(self, o):
        return (
            isinstance(o, FactorComputationNode)
            and self._name == o._name
            and self._factor == o._factor
        )

    def __hash__(self):
        return hash(("FactorComputationNode", self._name))


class FactorGraphLink(Link):
    def __init__(self, node1: str, node2: str):
        super().__init__([node1, node2], "factor_link")
        self._node1 = node1
        self._node2 = node2


class ComputationsFactorGraph(ComputationGraph):
    def __init__(self, var_nodes, factor_nodes):
        super().__init__("FactorGraph", list(var_nodes) + list(factor_nodes))
        self.var_nodes = list(var_nodes)
        self.factor_nodes = list(factor_nodes)


def build_computation_graph(dcop: Optional[DCOP] = None,
                            variables: Optional[Iterable[Variable]] = None,
                            constraints: Optional[Iterable[Constraint]] = None
                            ) -> ComputationsFactorGraph:
    """Build the factor graph (reference: factor_graph.py:245-288)."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    factors_of = {v.name: [] for v in variables}
    factor_nodes = []
    for c in constraints:
        factor_nodes.append(FactorComputationNode(c))
        for v in c.dimensions:
            factors_of.setdefault(v.name, []).append(c.name)

    var_nodes = [
        VariableComputationNode(v, factors_of[v.name]) for v in variables
    ]
    return ComputationsFactorGraph(var_nodes, factor_nodes)

"""Abstract computation-graph objects.

reference parity: pydcop/computations_graph/objects.py:37-329.
Nodes/links describe *what must be computed and who talks to whom*; they are
used by the distribution layer, the CLI ``graph`` command and tests.  The
hot-path representation used on device is the padded array form exported by
:mod:`pydcop_tpu.graphs.arrays`.
"""

from typing import Any, Iterable, List, Optional, Set

from ..utils.simple_repr import SimpleRepr


class Link(SimpleRepr):
    """A communication link between computation nodes."""

    def __init__(self, nodes: Iterable[str], link_type: str = "link"):
        self._nodes = tuple(sorted(nodes))
        self._link_type = link_type

    @property
    def nodes(self):
        return self._nodes

    @property
    def type(self) -> str:
        return self._link_type

    def has_node(self, name: str) -> bool:
        return name in self._nodes

    def __eq__(self, o):
        return (
            isinstance(o, Link)
            and self._nodes == o._nodes
            and self._link_type == o._link_type
        )

    def __hash__(self):
        return hash((self._nodes, self._link_type))

    def __repr__(self):
        return f"Link({self._link_type}, {self._nodes})"


class ComputationNode(SimpleRepr):
    """A node in a computation graph: one message-passing computation."""

    def __init__(self, name: str, node_type: str = "computation",
                 links: Optional[Iterable[Link]] = None):
        self._name = name
        self._node_type = node_type
        self._links = list(links) if links else []

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._node_type

    @property
    def links(self) -> List[Link]:
        return list(self._links)

    @property
    def neighbors(self) -> List[str]:
        seen, out = {self._name}, []
        for l in self._links:
            for n in l.nodes:
                if n not in seen:
                    seen.add(n)
                    out.append(n)
        return out

    def is_neighbor(self, other: str) -> bool:
        return other in self.neighbors

    def __eq__(self, o):
        return (
            isinstance(o, ComputationNode)
            and self._name == o._name
            and self._node_type == o._node_type
        )

    def __hash__(self):
        return hash((self._name, self._node_type))

    def __repr__(self):
        return f"ComputationNode({self._name!r}, {self._node_type!r})"

    def __str__(self):
        return self._name


class ComputationGraph:
    """A set of computation nodes + links."""

    def __init__(self, graph_type: str,
                 nodes: Optional[Iterable[ComputationNode]] = None):
        self._graph_type = graph_type
        self.nodes: List[ComputationNode] = list(nodes) if nodes else []

    @property
    def graph_type(self) -> str:
        return self._graph_type

    def computation(self, name: str) -> ComputationNode:
        for n in self.nodes:
            if n.name == name:
                return n
        raise KeyError(f"No computation {name} in graph")

    def computations(self) -> List[ComputationNode]:
        return list(self.nodes)

    def links_for_node(self, name: str) -> List[Link]:
        return [l for n in self.nodes if n.name == name for l in n.links]

    @property
    def links(self) -> List[Link]:
        out: Set[Link] = set()
        for n in self.nodes:
            out.update(n.links)
        return list(out)

    def density(self) -> float:
        """edges / edges-of-complete-graph (reference: objects.py:328)."""
        n = len(self.nodes)
        if n < 2:
            return 0.0
        e = len(self.links)
        return 2 * e / (n * (n - 1))

    def __len__(self):
        return len(self.nodes)

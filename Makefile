# reference parity: pyDCOP's Makefile (make test = unit + doctests +
# cli + api tiers).  Tests force the CPU backend with a virtual
# 8-device mesh (tests/conftest.py).

.PHONY: test test-fast bench suite lint

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -m "not slow"

bench:
	python bench.py

suite:
	python benchmarks/suite.py

lint:
	python -m compileall -q pydcop_tpu

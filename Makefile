# reference parity: pyDCOP's Makefile (make test = unit + doctests +
# cli + api tiers).  Tests force the CPU backend with a virtual
# 8-device mesh (tests/conftest.py).

.PHONY: test test-fast bench suite lint typecheck chaos bench-roi \
	bench-portfolio bench-autotune fleet trace-demo

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -m "not slow"

# the disruption tier: the chaos + checkpoint test markers (fault
# plans, kill->resume bit-exactness) plus the bench_chaos contract —
# whose preempt leg SIGKILLs a checkpointed solve mid-chunk and
# asserts the --resume run reproduces selections and cycles bit-exactly
chaos:
	python -m pytest tests/ -q -m "chaos or ckpt"
	python benchmarks/suite.py bench_chaos --quick

# the O(region) tier: the roi test marker plus the bench_roi ladder —
# perturbation sizes x graph sizes, asserting warm ms/event scales
# with the touched region (not |V|) and settled-region selections
# stay bit-identical to the full-sweep oracle
bench-roi:
	python -m pytest tests/ -q -m "roi"
	python benchmarks/suite.py bench_roi --quick

# the arm-race tier: the portfolio test marker plus the bench_portfolio
# contract — an 8-arm race on one loopy grid instance, asserting the
# winner matches the best solo arm, the race wall stays under 2x one
# arm (full mode), early kills reclaim >=50% of the naive 8x
# lane-cycles, and a mid-race kill -9 + --resume reproduces the
# uninterrupted winner bit-exactly
bench-portfolio:
	python -m pytest tests/ -q -m "portfolio"
	python benchmarks/suite.py bench_portfolio --quick

# the autotuner tier: the tuning test marker plus the bench_autotune
# contract — tune a small rung ladder through the real runners, then
# assert never-slower on every rung (the search argmin contains the
# default), a measured speedup on at least one rung, and that the
# sidecar-resolved winner stays bit-exact with the same config pinned
# explicitly
bench-autotune:
	python -m pytest tests/ -q -m "tuning"
	python benchmarks/suite.py bench_autotune --quick

# the scale-out tier: the fleet test marker (hash-ring determinism,
# router policy, failover re-send, release-op migration, repeatable
# serve-status) plus the bench_fleet contract — N real worker daemons
# behind the consistent-hash router, asserting throughput scale-out
# (core-gated), rolling restart with zero lost jobs and zero
# recompiles, and kill -9 failover whose migrated warm session stays
# bit-exact with the uninterrupted oracle
fleet:
	python -m pytest tests/ -q -m "fleet"
	python benchmarks/suite.py bench_fleet --quick

# the observability demo (ISSUE 20): run the bench_fleet quick
# contract with its telemetry kept under /tmp/pydcop_trace_demo —
# including the kill -9 failover leg — then validate the kill leg's
# directory (cross-file trace references must resolve) and render a
# failed-over job's reassembled span tree with `pydcop trace`.  The
# whole tracing pipeline, one target.
trace-demo:
	rm -rf /tmp/pydcop_trace_demo
	python benchmarks/trace_demo.py /tmp/pydcop_trace_demo

bench:
	python bench.py

suite:
	python benchmarks/suite.py

lint:
	python -m compileall -q pydcop_tpu

# reference parity: Makefile:21 (mypy --ignore-missing-imports).
# mypy is not baked into the benchmark image; install it in dev
# environments (`pip install mypy`) to run this tier.
typecheck:
	@python -c "import mypy" 2>/dev/null || \
	  (echo "mypy is not installed: pip install mypy" && exit 1)
	python -m mypy --ignore-missing-imports pydcop_tpu

"""Pure-Python threaded MaxSum baseline, reference-architecture style.

Faithful to the reference's execution model (SURVEY.md §3.3): one thread
per agent, each agent hosting computations, messages delivered through
synchronized per-agent queues, factor updates brute-forcing the joint
assignment space per neighbor in Python (maxsum.py:382-447).  Used by
bench.py to measure the msgs/sec the reference-style runtime achieves on
the same problem, for the vs_baseline ratio.

This is a re-implementation of the *architecture*, not a copy: agents,
queue delivery, per-message handler dispatch, per-neighbor min-marginal
loops.
"""

import itertools
import queue
import threading
import time
from collections import defaultdict


class Agent(threading.Thread):
    def __init__(self, name, network):
        super().__init__(daemon=True)
        self.name = name
        self.inbox = queue.PriorityQueue()
        self.network = network
        self.computations = {}
        self.running = True
        self.seq = 0
        self.handled = 0

    def post(self, dest_comp, msg):
        self.network.deliver(dest_comp, msg)

    def run(self):
        while self.running:
            try:
                _, _, (dest, msg) = self.inbox.get(timeout=0.05)
            except queue.Empty:
                continue
            comp = self.computations.get(dest)
            if comp is not None:
                comp.on_message(msg)
                self.handled += 1


class Network:
    def __init__(self):
        self.location = {}
        self.agents = {}
        self.msg_count = 0
        self.lock = threading.Lock()

    def register(self, comp_name, agent):
        self.location[comp_name] = agent

    def deliver(self, dest_comp, msg):
        agent = self.location[dest_comp]
        with self.lock:
            self.msg_count += 1
            agent.seq += 1
            seq = agent.seq
        agent.inbox.put((20, seq, (dest_comp, msg)))


class VariableComputation:
    def __init__(self, name, domain_size, unary, factors, agent):
        self.name = name
        self.D = domain_size
        self.unary = unary
        self.factors = factors
        self.agent = agent
        self.received = {}
        self.cycle_msgs = defaultdict(dict)
        # last cost table per factor, kept across rounds so the final
        # selection (argmin of belief) can be read after the run
        self.last_costs = {}

    def selection(self):
        belief = list(self.unary)
        for costs in self.last_costs.values():
            for d in range(self.D):
                belief[d] += costs[d]
        return min(range(self.D), key=lambda d: belief[d])

    def start(self):
        for f in self.factors:
            self.agent.post(f, ("var", self.name, 0, [0.0] * self.D))

    def on_message(self, msg):
        kind, sender, cycle, costs = msg
        self.received[sender] = costs
        self.last_costs[sender] = costs
        if len(self.received) >= len(self.factors):
            # send next-cycle messages: sum of other factors' costs
            for f in self.factors:
                out = list(self.unary)
                for f2, c in self.received.items():
                    if f2 != f:
                        for d in range(self.D):
                            out[d] += c[d]
                avg = sum(out) / self.D
                out = [v - avg for v in out]
                self.agent.post(f, ("var", self.name, cycle + 1, out))
            self.received = {}


class FactorComputation:
    def __init__(self, name, variables, domain_size, table, agent):
        self.name = name
        self.variables = variables
        self.D = domain_size
        self.table = table  # dict assignment-tuple -> cost
        self.agent = agent
        self.received = {}

    def on_message(self, msg):
        kind, sender, cycle, costs = msg
        self.received[sender] = costs
        if len(self.received) >= len(self.variables):
            # per neighbor: min-marginal over the full joint space
            # (reference maxsum.py:382-447 brute-force)
            for i, v in enumerate(self.variables):
                out = [float("inf")] * self.D
                others = [v2 for v2 in self.variables if v2 != v]
                for assignment in itertools.product(
                        range(self.D), repeat=len(others)):
                    for d in range(self.D):
                        full = list(assignment)
                        full.insert(i, d)
                        c = self.table[tuple(full)]
                        for j, v2 in enumerate(others):
                            c += self.received[v2][assignment[j]]
                        if c < out[d]:
                            out[d] = c
                self.agent.post(v, ("factor", self.name, cycle, out))
            self.received = {}


def run_maxsum_baseline(edges, n_vars, n_colors, var_costs,
                        duration: float = 5.0, n_agents: int = 8):
    """Run the threaded baseline for ``duration`` seconds; returns
    (msgs_delivered, elapsed)."""
    network = Network()
    agents = [Agent(f"a{i}", network) for i in range(n_agents)]

    factors_of = defaultdict(list)
    table = {}
    for d1 in range(n_colors):
        for d2 in range(n_colors):
            table[(d1, d2)] = 1.0 if d1 == d2 else 0.0

    comps = []
    for f, (u, v) in enumerate(edges):
        name = f"c{f}"
        agent = agents[f % n_agents]
        comp = FactorComputation(
            name, [f"v{u}", f"v{v}"], n_colors, table, agent)
        agent.computations[name] = comp
        network.register(name, agent)
        factors_of[u].append(name)
        factors_of[v].append(name)
        comps.append(comp)
    var_comps = []
    for i in range(n_vars):
        name = f"v{i}"
        agent = agents[i % n_agents]
        comp = VariableComputation(
            name, n_colors, list(var_costs[i]), factors_of[i], agent)
        agent.computations[name] = comp
        network.register(name, agent)
        var_comps.append(comp)

    for a in agents:
        a.start()
    t0 = time.perf_counter()
    for vc in var_comps:
        vc.start()
    time.sleep(duration)
    elapsed = time.perf_counter() - t0
    msgs = network.msg_count
    for a in agents:
        a.running = False
    for a in agents:
        a.join(timeout=1)
    selection = [vc.selection() for vc in var_comps]
    conflicts = sum(1 for u, v in edges if selection[u] == selection[v])
    return msgs, elapsed, conflicts

"""Process-isolated A/B of MaxSum step layouts (lane vs fused).

The round-4 methodology finding (PERF_NOTES): on the tunneled chip the
FIRST program compiled in a process runs ~1.6x faster than every later
one, so cross-program A/B inside one process is invalid.  This driver
runs ONE variant per child process, interleaved A/B/A/B..., and takes
per-variant bests across processes.

Usage:
    python benchmarks/ab_variants.py [--rounds 3] [--cycles 60]
    python benchmarks/ab_variants.py --child lane --cycles 60  # internal
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def child(variant: str, cycles: int):
    from functools import partial

    import jax
    import numpy as np

    # the A/B must measure EXACTLY the headline bench's problem: reuse
    # its construction (instance constants, seed, noise) so a bench
    # change can never silently desynchronize the comparison that
    # gates flipping the default layout
    import bench
    from pydcop_tpu.algorithms.maxsum import (MaxSumFusedSolver,
                                              MaxSumLaneSolver)

    os.environ.pop("PYDCOP_BENCH_LAYOUT", None)
    arrays, _ = bench._build(stability=0.0)
    cls = {"lane": MaxSumLaneSolver, "fused": MaxSumFusedSolver}[variant]
    solver = cls(arrays, damping=0.5, stability=0.0)

    @partial(jax.jit, donate_argnums=0)
    def run_k(s):
        return jax.lax.fori_loop(
            0, cycles, lambda i, st: solver.step(st), s)

    s = run_k(solver.init_state(jax.random.PRNGKey(0)))
    jax.block_until_ready(s["q"])
    best = float("inf")
    for _ in range(5):
        s0 = solver.init_state(jax.random.PRNGKey(0))
        jax.block_until_ready(s0["q"])
        t0 = time.perf_counter()
        s = run_k(s0)
        jax.block_until_ready(s["q"])
        best = min(best, time.perf_counter() - t0)
    sel = np.asarray(solver.assignment_indices(s))
    conflicts = bench._conflicts(arrays, sel)
    print("AB_RESULT " + json.dumps({
        "variant": variant,
        "msgs_per_sec": 2 * arrays.n_edges * cycles / best,
        "ms_per_cycle": best * 1000 / cycles,
        "conflicts": conflicts,
    }))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--cycles", type=int, default=60)
    p.add_argument("--child", choices=("lane", "fused"), default=None)
    args = p.parse_args()
    if args.child:
        child(args.child, args.cycles)
        return
    best = {"lane": None, "fused": None}
    for rnd in range(args.rounds):
        for variant in ("lane", "fused"):
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--child", variant, "--cycles", str(args.cycles)],
                    capture_output=True, text=True, timeout=900,
                    cwd=REPO)
            except subprocess.TimeoutExpired:
                # the tunneled chip's observed failure mode is a HANG,
                # not an exit: record and keep the A/B going
                print(f"round {rnd} {variant}: TIMEOUT (900s)")
                continue
            res = None
            for line in proc.stdout.splitlines():
                if line.startswith("AB_RESULT "):
                    res = json.loads(line[len("AB_RESULT "):])
            if res is None:
                print(f"round {rnd} {variant}: FAILED "
                      f"{proc.stderr.strip().splitlines()[-1:]}")
                continue
            print(f"round {rnd} {variant}: "
                  f"{res['msgs_per_sec'] / 1e6:.1f} M msgs/s "
                  f"({res['ms_per_cycle']:.3f} ms/cycle, "
                  f"{res['conflicts']} conflicts)")
            if best[variant] is None or res["msgs_per_sec"] > \
                    best[variant]["msgs_per_sec"]:
                best[variant] = res
    if best["lane"] and best["fused"]:
        ratio = best["fused"]["msgs_per_sec"] / \
            best["lane"]["msgs_per_sec"]
        print(json.dumps({
            "lane_best_msgs_per_sec": best["lane"]["msgs_per_sec"],
            "fused_best_msgs_per_sec": best["fused"]["msgs_per_sec"],
            "fused_over_lane": round(ratio, 4),
        }))


if __name__ == "__main__":
    main()

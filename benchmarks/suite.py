"""Benchmark suite: the BASELINE.md target configurations.

Runs each target config and prints one JSON line per benchmark (plus a
final summary line).  ``bench.py`` at the repo root stays the driver's
single headline metric; this suite is the full coverage:

1. 10-var/3-color coloring through the public solve API (the reference's
   CI envelope: correct assignment within seconds — BASELINE.md #1),
2. 1k-var damped A-MaxSum on a factor graph (#2),
3. DPOP UTIL/VALUE on a ~200-agent meeting-scheduling pseudo-tree (#3),
4. DSA-B and MGM-2 on a 10k-variable grid (#4),
5. batched instances vmapped across the chip (#5; pmapped over 8 devices
   when available).

Usage: python benchmarks/suite.py [--quick]
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def bench_solve_api_small():
    from pydcop_tpu.dcop.yamldcop import load_dcop
    from pydcop_tpu.infrastructure.run import solve_result

    yaml_src = """
name: gc10
objective: min
domains:
  colors: {values: [R, G, B]}
variables:
""" + "".join(
        f"  v{i}: {{domain: colors, cost_function: '0', "
        f"noise_level: 0.02}}\n"
        for i in range(10)) + "constraints:\n" + "".join(
        f"  c{i}: {{type: intention, function: 1 if v{i} == v{(i+1)%10} "
        f"else 0}}\n" for i in range(10)) + \
        "agents: [" + ", ".join(f"a{i}" for i in range(10)) + "]\n"
    dcop = load_dcop(yaml_src)
    t0 = time.perf_counter()
    res = solve_result(dcop, "maxsum", timeout=15)
    return {
        "metric": "solve_api_gc10_maxsum_seconds",
        "value": round(time.perf_counter() - t0, 3), "unit": "s",
        "cost": res.cost, "violations": res.violations,
        "status": res.status,
    }


def bench_amaxsum_1k(quick=False):
    import jax

    from pydcop_tpu.algorithms.amaxsum import AMaxSumSolver
    from pydcop_tpu.generators.fast import coloring_factor_arrays

    n = 200 if quick else 1000
    arrays = coloring_factor_arrays(n, 3 * n, 3, seed=11, noise=0.05)
    solver = AMaxSumSolver(arrays, activation=0.7, damping=0.5,
                           stability=0.0)
    k = 50

    @jax.jit
    def run_k(s):
        return jax.lax.fori_loop(0, k, lambda i, st: solver.step(st), s)

    state = solver.init_state(jax.random.PRNGKey(0))
    state = run_k(state)
    jax.block_until_ready(state["selection"])
    state = solver.init_state(jax.random.PRNGKey(0))
    t0 = time.perf_counter()
    state = run_k(state)
    jax.block_until_ready(state["selection"])
    elapsed = time.perf_counter() - t0
    msgs = 2 * arrays.n_edges * k
    return {
        "metric": f"amaxsum_{n}var_msgs_per_sec",
        "value": round(msgs / elapsed, 1), "unit": "msgs/s",
    }


def bench_dpop_meetings(quick=False):
    from pydcop_tpu.algorithms.dpop import solve_direct
    from pydcop_tpu.generators.meetingscheduling import generate_meetings

    # resources ~= events keeps the pseudo-tree's induced width small
    # (few events share a resource), so the exact DPOP tables stay
    # feasible at ~200 agents — the BASELINE.md #3 shape
    events = 20 if quick else 100
    dcop = generate_meetings(
        slots_count=6, events_count=events,
        resources_count=max(3, events), max_resources_event=2,
        seed=13)
    n_vars = len(dcop.variables)
    t0 = time.perf_counter()
    res = solve_direct(dcop, {}, timeout=120)
    return {
        "metric": f"dpop_meetings_{n_vars}vars_seconds",
        "value": round(time.perf_counter() - t0, 3), "unit": "s",
        "status": res.status, "violations": res.violations,
    }


def bench_dpop_device_widetree(quick=False):
    """BASELINE config 3 at the scale where the device UTIL sweep pays:
    wide-separator meeting scheduling (5 GB top table at slots=20).
    Reports the host-numpy path and the jitted device-spine path (cold
    = includes the one-time XLA compile; warm = steady state, the
    deployment regime where the same problem shape re-solves)."""
    from pydcop_tpu.algorithms.dpop import solve_direct
    from pydcop_tpu.generators.meetingscheduling import generate_meetings

    slots = 12 if quick else 20
    dcop = generate_meetings(
        slots_count=slots, events_count=150, resources_count=120,
        max_resources_event=2, seed=13)
    limit = 1_400_000_000
    r_cold = solve_direct(dcop, {"device": "jax"}, memory_limit=limit,
                          timeout=900)
    r_warm = solve_direct(dcop, {"device": "jax"}, memory_limit=limit,
                          timeout=900)
    r_host = solve_direct(dcop, {"device": "host"}, memory_limit=limit,
                          timeout=900)
    assert abs(r_host.cost - r_warm.cost) < 1e-3
    return {
        "metric": f"dpop_device_widetree_slots{slots}_seconds",
        "value": round(r_warm.duration, 3), "unit": "s",
        "host_seconds": round(r_host.duration, 3),
        "device_cold_seconds": round(r_cold.duration, 3),
        "device_speedup_warm": round(
            r_host.duration / r_warm.duration, 1),
        "cost": r_warm.cost, "violations": r_warm.violations,
    }


def bench_localsearch_10k(quick=False):
    import jax

    from pydcop_tpu.algorithms.dsa import DsaSolver
    from pydcop_tpu.algorithms.mgm2 import Mgm2Solver
    from pydcop_tpu.generators.fast import coloring_hypergraph_arrays

    n = 1024 if quick else 10_000
    side = int(n ** 0.5)
    n = side * side
    # grid edges (sensor-grid style)
    import numpy as np

    edges = []
    for r in range(side):
        for c in range(side):
            i = r * side + c
            if c + 1 < side:
                edges.append((i, i + 1))
            if r + 1 < side:
                edges.append((i, i + side))
    edges = np.array(edges, dtype=np.int32)
    arrays = coloring_hypergraph_arrays(n, len(edges), n_colors=4,
                                        seed=17, edges=edges)
    out = {}
    for name, solver in (
            ("dsa_b", DsaSolver(arrays, probability=0.7, variant="B")),
            ("mgm2", Mgm2Solver(arrays, threshold=0.5))):
        k = 20

        @jax.jit
        def run_k(s, _solver=solver):
            return jax.lax.fori_loop(
                0, k, lambda i, st: _solver.step(st), s)

        state = solver.init_state(jax.random.PRNGKey(0))
        state = run_k(state)
        jax.block_until_ready(state["x"])
        state = solver.init_state(jax.random.PRNGKey(0))
        t0 = time.perf_counter()
        state = run_k(state)
        jax.block_until_ready(state["x"])
        per_cycle = (time.perf_counter() - t0) / k
        out[name] = round(per_cycle * 1e3, 3)
    return {
        "metric": f"localsearch_{n}var_grid_ms_per_cycle",
        "value": out, "unit": "ms/cycle",
    }


def bench_batched(quick=False):
    import jax

    from pydcop_tpu.parallel.batch import BatchedMaxSum
    from pydcop_tpu.generators.fast import coloring_factor_arrays

    batch = 64 if quick else 1024
    template = coloring_factor_arrays(100, 300, 3, seed=19, noise=0.05)
    runner = BatchedMaxSum(template, batch=batch)
    t0 = time.perf_counter()
    selections, cycles, finished = runner.run(seed=0, max_cycles=50)
    jax.block_until_ready(selections)
    elapsed = time.perf_counter() - t0
    return {
        "metric": f"batched_{batch}x100var_instances_per_sec",
        "value": round(batch / elapsed, 1), "unit": "instances/s",
    }


def bench_mixed_hard_constraints(quick=False):
    """The mixed soft/hard family (generate mixed_problem) on its home
    algorithms: dba and mixeddsa drive the hard-constraint machinery
    end-to-end through the compiled engine."""
    from pydcop_tpu.generators.mixed import generate_mixed_problem
    from pydcop_tpu.infrastructure.run import solve_result

    n = 20 if quick else 60
    dcop = generate_mixed_problem(
        n, 0, hard_proportion=0.3, arity=2, domain_range=6,
        density=max(0.12, 6.0 / n), seed=23)
    out = {}
    for algo, params in (("mixeddsa", {"stop_cycle": 40}),
                         ("dba", {"max_distance": 20,
                                  "infinity": 10000})):
        t0 = time.perf_counter()
        res = solve_result(dcop, algo, timeout=120, **params)
        out[algo] = {
            "seconds": round(time.perf_counter() - t0, 3),
            "violations": res.violations,
            "status": res.status,
        }
    return {
        "metric": f"mixed_{n}var_hard30pct",
        "value": out, "unit": "per-algo",
    }


def bench_batched_localsearch(quick=False):
    """BatchedDsa / BatchedMgm campaign throughput (BASELINE config 5's
    local-search counterpart of bench_batched)."""
    import jax

    from pydcop_tpu.generators.fast import coloring_hypergraph_arrays
    from pydcop_tpu.parallel.batch import BatchedDsa, BatchedMgm

    batch = 64 if quick else 1024
    template = coloring_hypergraph_arrays(100, 300, 3, seed=19)
    out = {}
    for name, cls, kw in (
            ("dsa_b", BatchedDsa,
             {"probability": 0.7, "variant": "B"}),
            ("mgm", BatchedMgm, {})):
        runner = cls(template, batch=batch, **kw)
        t0 = time.perf_counter()
        selections, _cycles, _fin = runner.run(seed=0, max_cycles=50)
        jax.block_until_ready(selections)
        out[name] = round(batch / (time.perf_counter() - t0), 1)
    return {
        "metric": f"batched_localsearch_{batch}x100var_instances_per_sec",
        "value": out, "unit": "instances/s",
    }


_SHARDED_UTIL_CHILD = r"""
import json, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from pydcop_tpu.algorithms import dpop
from pydcop_tpu.dcop.yamldcop import load_dcop
from pydcop_tpu.generators.fast import clique_dcop_yaml

N, LIMIT = {n}, {limit}
src = clique_dcop_yaml(N, 8)
mesh = jax.sharding.Mesh(np.array(jax.devices()), ("tp",))

t0 = time.perf_counter()
dpop.solve_direct(load_dcop(src), device="jax", memory_limit=LIMIT,
                  mesh=mesh)
cold = time.perf_counter() - t0
t0 = time.perf_counter()
r_warm = dpop.solve_direct(load_dcop(src), device="jax",
                           memory_limit=LIMIT, mesh=mesh)
warm = time.perf_counter() - t0
t0 = time.perf_counter()
r_host = dpop.solve_direct(load_dcop(src), device="host",
                           memory_limit=8 ** 10)
host = time.perf_counter() - t0
print("CHILD_RESULT " + json.dumps({{
    "cold": cold, "warm": warm, "host": host,
    "dev_cost": r_warm.cost, "host_cost": r_host.cost}}))
"""


def bench_dpop_sharded_util(quick=False):
    """SURVEY §7 hard part (2) at beyond-one-device scale: an N-clique
    (domain 8) whose root UTIL table exceeds the per-device memory
    limit, so its leading separator axis is tp-sharded over an 8-device
    mesh (algorithms/dpop.py device_util_sweep).

    Runs in a subprocess on the virtual 8-device CPU mesh: a single
    physical chip cannot host a tp=8 mesh, so the honest evidence here
    is (a) EXACTNESS — the sharded sweep reproduces the host optimum —
    and (b) MEMORY scale-out — per-device bytes are 1/8th of the
    monolithic table (537 MB -> 67 MB at N=9).  Wall-clock device-vs-
    host on virtual devices compares XLA-CPU against vectorized numpy
    on the same silicon and is reported but NOT a hardware speedup
    claim (the single-device widetree entry above carries the real-chip
    speedup)."""
    import os
    import subprocess

    n = 8 if quick else 9
    limit = 4_000_000 if quick else 20_000_000
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "-c",
         _SHARDED_UTIL_CHILD.format(n=n, limit=limit)],
        capture_output=True, text=True, timeout=420, env=env, cwd=repo)
    child = None
    for line in proc.stdout.splitlines():
        if line.startswith("CHILD_RESULT "):
            child = json.loads(line[len("CHILD_RESULT "):])
    if child is None:
        raise RuntimeError(
            (proc.stderr.strip().splitlines() or ["no output"])[-1][:200])
    total_cells = 8 ** n
    return {
        "metric": f"dpop_sharded_util_{n}clique_domain8_seconds",
        "value": round(child["warm"], 3), "unit": "s",
        "host_seconds": round(child["host"], 3),
        "device_cold_seconds": round(child["cold"], 3),
        "table_mb_total": round(total_cells * 4 / 2 ** 20, 1),
        "table_mb_per_device": round(total_cells * 4 / 8 / 2 ** 20, 1),
        "cost": child["dev_cost"],
        "sharded_equals_host": bool(
            child["dev_cost"] == child["host_cost"]),
        "virtual_mesh": True,
    }


_MESH_DISPATCH_CHILD = r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
from pydcop_tpu.generators.fast import coloring_factor_arrays
from pydcop_tpu.parallel import make_mesh
from pydcop_tpu.parallel.sharded_maxsum import ShardedMaxSum

MODE, K, N, CYCLES = "{mode}", {k}, {n}, {cycles}
# the round-5 mesh shape: 10k vars / 30k edges / 3 colors, lane
# layout, 4 batched instances on the dp axis of the (4, 2) mesh;
# stability=0 disables convergence so every leg times the same
# CYCLES cycles
arrays = coloring_factor_arrays(N, 3 * N, 3, seed=17, noise=0.05)
sm = ShardedMaxSum(arrays, make_mesh(8), damping=0.5, stability=0.0,
                   batch=4)
run = sm.run_eager if MODE == "eager" else (
    lambda c: sm.run(c, chunk_size=K))
run(2)                          # compile warm-up, same program
t0 = time.perf_counter()
sel, cycles = run(CYCLES)
elapsed = time.perf_counter() - t0
print("CHILD_RESULT " + json.dumps({{
    "ms_per_cycle": elapsed * 1e3 / cycles, "cycles": cycles,
    "dispatches": sm.last_run_stats["dispatches"],
    "host_syncs": sm.last_run_stats["host_syncs"]}}))
"""


def bench_mesh_dispatch(quick=False):
    """Eager-per-cycle vs the chunked mesh engine (ISSUE 2 tentpole):
    the SAME sharded MaxSum program driven (a) one jitted dispatch +
    one sel/delta device->host transfer per cycle — the pre-engine
    run loop — and (b) K cycles per dispatch inside one compiled
    ``lax.while_loop`` with on-device convergence, K in {1, 8, 32}.

    Process-isolated (one leg per process, fresh XLA) on the virtual
    8-device CPU mesh; host numbers time XLA-CPU collectives and
    Python dispatch on the same silicon and are labeled as such, not
    chip evidence.  The host-sync counter verifies the engine
    contract: at most ceil(cycles / K) + 1 syncs per run."""
    import math
    import os
    import subprocess

    n = 1024 if quick else 10_000
    cycles = 30
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    legs = [("eager", 1)] + [("chunked", k) for k in (1, 8, 32)]
    out = {}
    contract_ok = True
    for mode, k in legs:
        proc = subprocess.run(
            [sys.executable, "-c", _MESH_DISPATCH_CHILD.format(
                mode=mode, k=k, n=n, cycles=cycles)],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=repo)
        child = None
        for line in proc.stdout.splitlines():
            if line.startswith("CHILD_RESULT "):
                child = json.loads(line[len("CHILD_RESULT "):])
        if child is None:
            raise RuntimeError(
                (proc.stderr.strip().splitlines()
                 or ["no output"])[-1][:300])
        name = mode if mode == "eager" else f"chunked_k{k}"
        out[name] = {
            "ms_per_cycle": round(child["ms_per_cycle"], 3),
            "host_syncs": child["host_syncs"],
            "dispatches": child["dispatches"],
        }
        if mode == "chunked":
            contract_ok = contract_ok and (
                child["host_syncs"]
                <= math.ceil(cycles / k) + 1)
    for name in ("chunked_k1", "chunked_k8", "chunked_k32"):
        out[name]["vs_eager"] = round(
            out["eager"]["ms_per_cycle"] / out[name]["ms_per_cycle"],
            2)
    import jax

    return {
        "metric": f"mesh_dispatch_ab_{n}var_ms_per_cycle",
        "value": out, "unit": "ms/cycle",
        "cycles": cycles,
        "sync_contract_ok": contract_ok,
        "hardware": jax.default_backend(),
        "virtual_mesh": True,
    }


def bench_batch_campaign_fused(quick=False):
    """The 1024-instance campaign THROUGH the campaign tooling (VERDICT
    r4 item 8): batch YAML -> fused vmapped program (commands/batch.py
    `_run_fused_group` -> parallel/batch.py) -> per-job JSONs ->
    consolidate CSV.  End-to-end wall clock, including job expansion,
    instance loading and the 1024 result files — the number a campaign
    user actually experiences, not just the solver's inner loop."""
    import csv
    import io
    import os
    import shutil
    import subprocess
    import tempfile

    iterations = 64 if quick else 1024
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo)
    work = tempfile.mkdtemp(prefix="pydcop_campaign_")
    try:
        inst = os.path.join(work, "inst.yaml")
        subprocess.run(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-o", inst,
             "generate", "graph_coloring", "-v", "100", "-c", "3",
             "-g", "random", "--p_edge", "0.05", "--soft",
             "--seed", "7"],
            check=True, capture_output=True, timeout=120, env=env,
            cwd=repo)
        bench_yaml = os.path.join(work, "bench.yaml")
        with open(bench_yaml, "w") as f:
            f.write(f"""
sets:
  s1:
    path: '{inst}'
    iterations: {iterations}
batches:
  campaign:
    command: solve
    command_options:
      algo: [dsa]
      max_cycles: 30
""")
        out_dir = os.path.join(work, "out")
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "batch",
             bench_yaml, "--dir", out_dir],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=repo)
        elapsed = time.perf_counter() - t0
        if proc.returncode != 0 or f"fused x{iterations}" \
                not in proc.stdout:
            raise RuntimeError(
                f"campaign did not fuse: rc={proc.returncode} "
                f"{proc.stderr[-200:]}")
        cons = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli",
             "consolidate", os.path.join(out_dir, "*.json")],
            capture_output=True, text=True, timeout=300, check=True,
            env=env, cwd=repo)
        rows = list(csv.DictReader(io.StringIO(cons.stdout)))
        if len(rows) != iterations:
            raise RuntimeError(
                f"consolidate saw {len(rows)} rows, "
                f"expected {iterations}")
        return {
            "metric": f"batch_campaign_fused_{iterations}x100var"
                      f"_instances_per_sec",
            "value": round(iterations / elapsed, 1),
            "unit": "instances/s",
            "campaign_seconds": round(elapsed, 2),
            "consolidated_rows": len(rows),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def bench_hetero_batch(quick=False):
    """Heterogeneous campaign A/B (ISSUE 3 tentpole): ~256 mixed-size
    coloring + Ising jobs through the campaign tooling, (a) one
    subprocess per job (--no-fuse --parallel, measured on a subset and
    reported as-is — the per-job cost is constant, dominated by CLI
    startup + XLA retrace) vs (b) shape-bucketed fused
    (--fuse-hetero): instances padded into the power-of-two ladder run
    as <= #rungs compiled programs.

    Contract asserted: programs <= rungs < #distinct topologies,
    reported padding waste <= 2.0x total cells, and end-to-end
    campaign inst/s beats the subprocess path.  Process-isolated legs;
    numbers are host-CPU (XLA-CPU + subprocess startup on the same
    silicon) per the round-4 protocol, not chip evidence."""
    import os
    import re
    import shutil
    import subprocess
    import tempfile

    iterations = 8 if quick else 32
    sub_iterations = 1 if quick else 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    work = tempfile.mkdtemp(prefix="pydcop_hetero_")
    try:
        from pydcop_tpu.dcop.yamldcop import dcop_yaml
        from pydcop_tpu.generators.graphcoloring import \
            generate_graph_coloring
        from pydcop_tpu.generators.ising import generate_ising

        # 8 distinct topologies: 6 soft colorings in two size bands
        # (each band shares a pow2 rung) + 2 Ising grids
        topo = 0
        for nv in (20, 24, 28, 36, 44, 48):
            # scale-free: deterministic edge count 2(n-2), so each
            # size band lands on one pow2 rung by construction
            dcop = generate_graph_coloring(
                nv, 3, "scalefree", m_edge=2, soft=True, seed=nv)
            with open(os.path.join(work, f"i{topo}.yaml"), "w") as f:
                f.write(dcop_yaml(dcop))
            topo += 1
        for side in (4, 5):
            with open(os.path.join(work, f"i{topo}.yaml"), "w") as f:
                f.write(dcop_yaml(generate_ising(side, side,
                                                 seed=side)))
            topo += 1

        def bench_yaml(path, its):
            with open(path, "w") as f:
                f.write(f"""
sets:
  s1:
    path: '{work}/i*.yaml'
    iterations: {its}
batches:
  campaign:
    command: solve
    command_options:
      algo: [dsa]
      max_cycles: 30
""")

        # fused leg: the whole campaign, one process
        fused_yaml = os.path.join(work, "bench_fused.yaml")
        bench_yaml(fused_yaml, iterations)
        n_jobs = topo * iterations
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "batch",
             fused_yaml, "--fuse-hetero",
             "--dir", os.path.join(work, "out_fused")],
            capture_output=True, text=True, timeout=1200, env=env,
            cwd=repo)
        fused_s = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(f"fused leg rc={proc.returncode}: "
                               f"{proc.stderr[-300:]}")
        m = re.search(r"\[fuse-hetero\] jobs=(\d+) programs=(\d+) "
                      r"rungs=(\d+) waste=([\d.]+)", proc.stdout)
        if not m:
            raise RuntimeError("no [fuse-hetero] stats line "
                               f"in: {proc.stdout[-300:]}")
        jobs_f, programs, rungs, waste = (
            int(m.group(1)), int(m.group(2)), int(m.group(3)),
            float(m.group(4)))
        contract_ok = (jobs_f == n_jobs and programs <= rungs
                       and rungs < topo and waste <= 2.0)
        if not contract_ok:
            raise RuntimeError(
                f"hetero contract violated: jobs={jobs_f}/{n_jobs} "
                f"programs={programs} rungs={rungs} (topologies="
                f"{topo}) waste={waste}")

        # subprocess leg: same campaign shape, subset of iterations
        # (per-job cost is constant: CLI startup + XLA retrace each)
        sub_yaml = os.path.join(work, "bench_sub.yaml")
        bench_yaml(sub_yaml, sub_iterations)
        n_sub = topo * sub_iterations
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "batch",
             sub_yaml, "--no-fuse", "--parallel", "8",
             "--dir", os.path.join(work, "out_sub")],
            capture_output=True, text=True, timeout=1200, env=env,
            cwd=repo)
        sub_s = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(f"subprocess leg rc={proc.returncode}: "
                               f"{proc.stderr[-300:]}")
        fused_ips = round(n_jobs / fused_s, 1)
        sub_ips = round(n_sub / sub_s, 1)
        if fused_ips <= sub_ips:
            raise RuntimeError(
                f"fused {fused_ips} inst/s did not beat subprocess "
                f"{sub_ips} inst/s")
        return {
            "metric": f"hetero_batch_ab_{n_jobs}job_instances_per_sec",
            "value": {"bucketed_fused": fused_ips,
                      "subprocess_per_job": sub_ips},
            "unit": "instances/s",
            "speedup": round(fused_ips / sub_ips, 1),
            "topologies": topo,
            "compiled_programs": programs,
            "ladder_rungs": rungs,
            "padding_waste": waste,
            "contract_ok": contract_ok,
            "subprocess_jobs_measured": n_sub,
            "hardware": "cpu-host",
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def bench_serve(quick=False):
    """Solver-as-a-service A/B (ISSUE 9 tentpole): one burst of mixed
    jobs (6 coloring topologies sharing two pow2 rungs, dsa + maxsum)
    through stdin `serve` daemons, (a) sequential per-job dispatch
    (--max-batch 1: every job runs alone, the no-dynamic-batching
    control) vs (b) dynamic batching (--max-batch 8
    --max-delay-ms 100: rungs fill or deadline-fire).

    Each leg runs its daemon TWICE against a shared executable cache
    and measures the SECOND (warm-restarted) process — the steady
    state of a service, where cold rungs deserialize instead of
    compiling; the cold run's compile span total is reported alongside
    the warm remainder as the cache's measured saving.  Per-job
    latency is the summary records' ``queue_wait_s`` (admission ->
    dispatch completion, so it includes time queued behind earlier
    dispatches); throughput is completed jobs over the daemon's own
    ``uptime_s`` (serving time, interpreter/jax startup excluded).
    Contract asserted: warm dynamic batching beats warm sequential
    dispatch on throughput with fewer dispatches, WITHOUT degrading
    p99 latency.  Process-isolated legs, host-CPU numbers (XLA-CPU),
    per the round-4 protocol."""
    import os
    import shutil
    import subprocess
    import tempfile

    # enough jobs that SUSTAINED dispatch dominates the one-time warm
    # costs both legs share (per-runner deserializes, first-touch
    # admission builds) — at 32 jobs the legs tie on shared fixed cost
    n_jobs = 160 if quick else 480
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    work = tempfile.mkdtemp(prefix="pydcop_serve_")
    try:
        from pydcop_tpu.dcop.yamldcop import dcop_yaml
        from pydcop_tpu.generators.graphcoloring import \
            generate_graph_coloring
        from pydcop_tpu.observability.report import read_records

        # two size bands -> two pow2 rungs per algo family.  Jobs are
        # deliberately SMALL and short (service-shaped: the per-job
        # device work is milliseconds, so per-dispatch fixed costs —
        # Python dispatch, arg stacking, device round-trips — are the
        # quantity under test; dynamic batching amortizes exactly
        # those)
        # sizes chosen so each band shares ONE home rung per algo
        # family: vars 12/14/16 -> pow2 17 with 2(n-2) edges 20/24/28
        # -> 32 slots; vars 20/24/28 -> 33 with edges 36/44/52 -> 64
        bands = {"small": [], "big": []}
        for nv in (12, 14, 16, 20, 24, 28):
            dcop = generate_graph_coloring(
                nv, 3, "scalefree", m_edge=2, soft=True, seed=nv)
            p = os.path.join(work, f"i{nv}.yaml")
            with open(p, "w") as f:
                f.write(dcop_yaml(dcop))
            bands["small" if nv <= 16 else "big"].append(p)
        # round-robin over the four (algo x size-band) groups so every
        # group sees n_jobs/4 jobs — the load is mixed WITHIN each
        # dispatch window without skewing group sizes (a lopsided mix
        # only measures the skew, not the dispatch policy)
        group_of = [("maxsum", "small"), ("dsa", "small"),
                    ("maxsum", "big"), ("dsa", "big")]
        jobs = []
        for i in range(n_jobs):
            algo, band = group_of[i % 4]
            jobs.append(json.dumps({
                "id": f"j{i}",
                "dcop": bands[band][(i // 4) % len(bands[band])],
                "algo": algo, "max_cycles": 10, "seed": i}))
        jobs_text = "".join(j + "\n" for j in jobs)

        def run_daemon(tag, max_batch, max_delay_ms, exec_dir, run_i,
                       extra=()):
            out = os.path.join(work, f"{tag}_{run_i}.jsonl")
            proc = subprocess.run(
                [sys.executable, "-m", "pydcop_tpu.dcop_cli", "serve",
                 "--out", out, "--exec-cache", exec_dir,
                 "--max-batch", str(max_batch),
                 "--max-delay-ms", str(max_delay_ms), *extra],
                input=jobs_text, capture_output=True, text=True,
                timeout=1800, env=env, cwd=repo)
            if proc.returncode != 0:
                raise RuntimeError(f"{tag} leg rc={proc.returncode}: "
                                   f"{proc.stderr[-300:]}")
            return read_records(out)

        def leg(tag, max_batch, max_delay_ms):
            exec_dir = os.path.join(work, f"exec_{tag}")
            cold = run_daemon(tag, max_batch, max_delay_ms, exec_dir, 0)
            warm = run_daemon(tag, max_batch, max_delay_ms, exec_dir, 1)

            def span_total(records, key):
                return sum(
                    r["spans"].get(key, 0.0) for r in records
                    if r.get("record") == "serve"
                    and r.get("event") == "dispatch")

            waits = sorted(
                r["queue_wait_s"] for r in warm
                if r.get("record") == "summary"
                and r.get("status") != "REJECTED")
            if len(waits) != n_jobs:
                raise RuntimeError(
                    f"{tag} leg completed {len(waits)}/{n_jobs}")
            final = warm[-1]
            if final.get("record") != "serve" \
                    or final.get("event") != "drained":
                raise RuntimeError(
                    f"{tag} warm leg did not end with the drained "
                    f"serve record: {final}")
            uptime = final["uptime_s"]
            dispatches = sum(
                1 for r in warm if r.get("record") == "serve"
                and r.get("event") == "dispatch")
            return {
                "throughput_jobs_per_s": round(n_jobs / uptime, 2),
                "p50_latency_s": round(waits[len(waits) // 2], 4),
                "p99_latency_s": round(
                    waits[min(len(waits) - 1,
                              int(len(waits) * 0.99))], 4),
                "dispatches": dispatches,
                "uptime_s": round(uptime, 3),
                "cold_compile_s": round(sum(
                    span_total(cold, k) for k in
                    ("compile_s", "trace_lower_s", "eval_compile_s",
                     "eval_trace_lower_s")), 3),
                "warm_compile_s": round(sum(
                    span_total(warm, k) for k in
                    ("compile_s", "trace_lower_s", "eval_compile_s",
                     "eval_trace_lower_s")), 3),
                "warm_deserialize_s": round(
                    span_total(warm, "deserialize_s")
                    + span_total(warm, "eval_deserialize_s"), 3),
            }

        # sequential dispatches immediately (max_batch 1), so its
        # deadline is inert; the dynamic deadline is tuned to ~2x the
        # per-dispatch service time — tighter and partially-filled
        # rungs deadline-fire behind slow dispatches, fragmenting the
        # batch-size universe (each fragment size is its own compiled
        # program + warm deserialize)
        seq = leg("sequential", 1, 25)
        dyn = leg("dynamic", 8, 100)
        contract_ok = (
            dyn["throughput_jobs_per_s"] > seq["throughput_jobs_per_s"]
            and dyn["p99_latency_s"] <= seq["p99_latency_s"]
            and dyn["dispatches"] < seq["dispatches"])
        if not contract_ok:
            raise RuntimeError(
                f"serve contract violated: dynamic {dyn} vs "
                f"sequential {seq}")

        # --- instrumentation-overhead leg (ISSUE 11): the ops plane
        # (registry counters/histograms + per-job trace records +
        # 0.5 s heartbeats) vs --no-metrics, both WARM against a
        # shared executable cache.  Best-of-two warm runs per arm so
        # host-CPU scheduler noise does not masquerade as overhead;
        # the contract is the acceptance criterion: < 5% throughput
        # cost on the dispatch path.
        def warm_throughput(tag, extra):
            exec_dir = os.path.join(work, "exec_overhead")
            run_daemon(tag, 8, 100, exec_dir, 0, extra)  # warm-up
            best = 0.0
            for run_i in (1, 2):
                records = run_daemon(tag, 8, 100, exec_dir, run_i,
                                     extra)
                final = records[-1]
                if final.get("event") != "drained":
                    raise RuntimeError(
                        f"{tag} overhead leg did not drain: {final}")
                done = sum(1 for r in records
                           if r.get("record") == "summary"
                           and r.get("status") != "REJECTED")
                if done != n_jobs:
                    raise RuntimeError(
                        f"{tag} overhead leg completed "
                        f"{done}/{n_jobs}")
                best = max(best, n_jobs / final["uptime_s"])
            return round(best, 2)

        plain_tp = warm_throughput("ops_plain", ("--no-metrics",))
        inst_tp = warm_throughput(
            "ops_instrumented", ("--heartbeat-s", "0.5"))
        overhead_pct = round(
            100.0 * (plain_tp - inst_tp) / plain_tp, 2)
        if overhead_pct >= 5.0:
            raise RuntimeError(
                f"ops-plane instrumentation costs {overhead_pct}% "
                f"throughput (plain {plain_tp} vs instrumented "
                f"{inst_tp} jobs/s); the <5% dispatch-path budget is "
                f"blown")
        overhead = {
            "plain_jobs_per_s": plain_tp,
            "instrumented_jobs_per_s": inst_tp,
            "overhead_pct": overhead_pct,
            "contract": "instrumented >= 95% of plain throughput",
        }
        return {
            "metric": f"serve_ab_{n_jobs}job_burst_warm_restart",
            "value": {"dynamic_batching": dyn, "sequential": seq,
                      "instrumentation_overhead": overhead},
            "unit": "jobs/s + latency percentiles",
            "speedup": round(dyn["throughput_jobs_per_s"]
                             / seq["throughput_jobs_per_s"], 2),
            "contract_ok": contract_ok,
            "hardware": "cpu-host",
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def _nary_ab_one(solvers, n_edges, k=30):
    """msgs/s per named solver on the SAME instance, same-program
    best-of-3 each; adds fast-vs-generic speedups and a selections
    cross-check."""
    import jax
    import numpy as np

    out = {}
    sel_by_path = {}
    for name, solver in solvers.items():

        @jax.jit
        def run_k(s, _solver=solver):
            return jax.lax.fori_loop(
                0, k, lambda i, st: _solver.step(st), s)

        state = run_k(solver.init_state(jax.random.PRNGKey(0)))
        jax.block_until_ready(state["q"])  # warm-up / compile
        best = float("inf")
        for _ in range(3):
            state = solver.init_state(jax.random.PRNGKey(0))
            t0 = time.perf_counter()
            state = run_k(state)
            jax.block_until_ready(state["q"])
            best = min(best, time.perf_counter() - t0)
        out[name] = round(2 * n_edges * k / best, 1)
        sel_by_path[name] = np.asarray(
            jax.device_get(solver.assignment_indices(state)))
    sels = list(sel_by_path.values())
    out["selections_equal"] = bool(all(
        np.array_equal(sels[0], s) for s in sels[1:]))
    out["lane_vs_generic"] = round(out["lane"] / out["generic"], 2)
    out["fused_vs_generic"] = round(out["fused"] / out["generic"], 2)
    return out


def bench_nary_fastpath(quick=False):
    """N-ary factor fast path A/B on the reference's marquee n-ary
    families: PEAV meeting scheduling (k-ary event-equality encoding)
    and SECP, plus the at-scale synthetic mixed-arity shape.

    ``generic`` is the PRE-fast-path reality for these models — arrays
    built in model constraint order (non-canonical), taking the
    gather/scatter XLA path; ``lane`` / ``fused`` are the arity-
    bucketed fast layouts on the arity-sorted canonical build of the
    SAME instance.  ``hardware`` is labeled honestly per bench.py
    convention: this process runs on whatever backend jax resolved,
    and a CPU number is never presented as chip evidence."""
    import numpy as np

    import jax

    from pydcop_tpu.algorithms.maxsum import (MaxSumFusedSolver,
                                              MaxSumLaneSolver,
                                              MaxSumSolver)
    from pydcop_tpu.dcop.dcop import filter_dcop
    from pydcop_tpu.generators.fast import nary_factor_arrays
    from pydcop_tpu.generators.meetingscheduling import generate_meetings
    from pydcop_tpu.generators.secp import generate_secp
    from pydcop_tpu.graphs.arrays import FactorGraphArrays

    rng = np.random.default_rng(0)

    def legs_for(dcop):
        a_canon = FactorGraphArrays.build(dcop, arity_sorted=True)
        # tiny unary noise breaks the generators' exact belief ties so
        # the selections cross-check is meaningful
        tie = rng.uniform(0, 1e-3, a_canon.var_costs.shape) \
            .astype(np.float32)
        a_canon.var_costs = a_canon.var_costs + tie
        a_raw = FactorGraphArrays.build(dcop, arity_sorted=False)
        a_raw.var_costs = a_raw.var_costs + tie
        kw = dict(damping=0.5, stability=0.0)
        return {
            "generic": MaxSumSolver(a_raw, **kw),
            "lane": MaxSumLaneSolver(a_canon, **kw),
            "fused": MaxSumFusedSolver(a_canon, **kw),
        }, a_canon.n_edges

    peav = filter_dcop(generate_meetings(
        slots_count=6, events_count=40 if quick else 600,
        resources_count=30 if quick else 400, max_resources_event=3,
        seed=13, nary_equalities=True))
    secp = filter_dcop(generate_secp(
        lights_count=12 if quick else 60,
        models_count=8 if quick else 40, rules_count=4, seed=7))
    out = {
        "peav_nary": _nary_ab_one(*legs_for(peav)),
        "secp": _nary_ab_one(*legs_for(secp)),
    }
    # the at-scale mixed-arity shape without the host object model
    # (canonical by construction, so generic-vs-fast here compares
    # against the reshape form of the generic path)
    synth = nary_factor_arrays(
        200 if quick else 2000,
        {2: 300 if quick else 3000, 3: 100 if quick else 1000,
         4: 30 if quick else 300}, n_values=3, seed=5)
    kw = dict(damping=0.5, stability=0.0)
    out["mixed_synth"] = _nary_ab_one({
        "generic": MaxSumSolver(synth, **kw),
        "lane": MaxSumLaneSolver(synth, **kw),
        "fused": MaxSumFusedSolver(synth, **kw),
    }, synth.n_edges)
    return {
        "metric": "nary_fastpath_ab_msgs_per_sec",
        "value": out, "unit": "msgs/s",
        "hardware": jax.default_backend(),
    }


_PRECISION_MESH_CHILD = r"""
import hashlib, json, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from pydcop_tpu.generators.fast import coloring_factor_arrays
from pydcop_tpu.parallel import make_mesh
from pydcop_tpu.parallel.sharded_maxsum import ShardedMaxSum

PREC, N, CYCLES = "{prec}", {n}, {cycles}
# the round-5 mesh shape with INTEGER costs (noise=0), so the bf16
# policy's bit-exactness contract applies; stability=0 disables
# convergence so both legs time the same CYCLES cycles
arrays = coloring_factor_arrays(N, 3 * N, 3, seed=17, noise=0.0)
sm = ShardedMaxSum(arrays, make_mesh(8), damping=0.5, stability=0.0,
                   batch=4, precision=PREC)
sm.run(2, chunk_size=32)                # compile warm-up, same program
t0 = time.perf_counter()
sel, cycles = sm.run(CYCLES, chunk_size=32)
elapsed = time.perf_counter() - t0

# HLO bytes-accessed census of ONE compiled sharded cycle — the mesh
# step takes its cost planes as ARGUMENTS (device-placed constants),
# so the census measures real plane reads; a census of the
# single-chip solver would lie here, because XLA constant-folds the
# bf16->f32 upcast of closure-constant cubes into f32 constants.
# The census itself is the promoted observability surface
# (pydcop_tpu/observability/hlo.py), the same numbers telemetry runs
# report as RunResult.compile_stats


def census(solver):
    from pydcop_tpu.observability.hlo import bytes_accessed
    state, consts = solver._device_put()
    args = solver._step_args(consts)
    return bytes_accessed(solver._step, state["q"], state["r"],
                          jax.random.PRNGKey(0), *args)


# two shapes: binary D=3 coloring (message planes dominate the bytes,
# the cube halving is a small slice) and the arity-3 PEAV/SECP shape
# (D**3 hypercubes dominate, where the halving actually bites)
from pydcop_tpu.generators.fast import nary_factor_arrays
nary = nary_factor_arrays(max(64, N // 8), {{3: max(128, N // 4)}},
                          n_values=3, seed=5)
sm3 = ShardedMaxSum(nary, make_mesh(8), damping=0.5, stability=0.0,
                    batch=4, precision=PREC)
print("CHILD_RESULT " + json.dumps({{
    "ms_per_cycle": elapsed * 1e3 / cycles,
    "bytes_accessed": census(sm),
    "bytes_accessed_arity3": census(sm3),
    "sel_sha": hashlib.sha256(
        np.ascontiguousarray(np.asarray(sel, dtype=np.int32))
        .tobytes()).hexdigest()}}))
"""


def bench_precision(quick=False):
    """Mixed-precision A/B (ISSUE 4 tentpole): the SAME programs at
    f32 vs bf16 cost planes.

    Leg 1 — 10k-var mesh MaxSum (4 instances on the virtual 8-device
    CPU mesh), process-isolated per precision: ms/cycle, plus an HLO
    bytes-accessed census of one compiled single-chip cycle so the
    bandwidth claim is the compiler's accounting, not an assertion.
    Leg 2 — a 256-job mixed-topology fused campaign (--fuse-hetero
    --precision X) through the batch CLI: inst/s per precision.

    Contract asserted IN the bench: identical selections across
    precisions on both legs (integer-cost instances), and a strictly
    smaller bytes-accessed census for the bf16 leg.  Numbers are
    host-CPU (XLA-CPU on the same silicon, per the round-4 protocol)
    — the BYTES census is hardware-independent; the ms/cycle is not
    chip evidence (XLA-CPU upcasts bf16 lanes for compute, so the
    wall-clock win is expected on TPU, where bf16 is native, not
    here)."""
    import glob
    import json as _json
    import os
    import shutil
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.pop("PYDCOP_TPU_PRECISION", None)
    n = 1024 if quick else 10_000
    cycles = 30
    mesh_out = {}
    for prec in ("f32", "bf16"):
        proc = subprocess.run(
            [sys.executable, "-c", _PRECISION_MESH_CHILD.format(
                prec=prec, n=n, cycles=cycles)],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=repo)
        child = None
        for line in proc.stdout.splitlines():
            if line.startswith("CHILD_RESULT "):
                child = _json.loads(line[len("CHILD_RESULT "):])
        if child is None:
            raise RuntimeError(
                (proc.stderr.strip().splitlines()
                 or ["no output"])[-1][:300])
        mesh_out[prec] = child
    if mesh_out["f32"]["sel_sha"] != mesh_out["bf16"]["sel_sha"]:
        raise RuntimeError(
            "precision contract violated: bf16 mesh selections "
            "diverged from f32 on an integer-cost instance")
    bytes_f32 = mesh_out["f32"]["bytes_accessed"]
    bytes_bf16 = mesh_out["bf16"]["bytes_accessed"]
    bytes3_f32 = mesh_out["f32"]["bytes_accessed_arity3"]
    bytes3_bf16 = mesh_out["bf16"]["bytes_accessed_arity3"]
    if not (bytes_bf16 < bytes_f32 and bytes3_bf16 < bytes3_f32):
        raise RuntimeError(
            f"precision contract violated: bf16 bytes accessed "
            f"({bytes_bf16}, arity3 {bytes3_bf16}) not below f32 "
            f"({bytes_f32}, arity3 {bytes3_f32})")

    # ---- leg 2: 256-job mixed fused campaign through the batch CLI
    iterations = 8 if quick else 32
    work = tempfile.mkdtemp(prefix="pydcop_precision_")
    try:
        from pydcop_tpu.dcop.yamldcop import dcop_yaml
        from pydcop_tpu.generators.graphcoloring import \
            generate_graph_coloring

        topo = 0
        for nv in (20, 24, 28, 36, 44, 48, 52, 60):
            # noise_level=0 keeps every cost integral (cost-1
            # conflicts, zero unary noise): the bit-exact contract
            # applies — the default 0.02 noisy preferences would put
            # the campaign on the documented-tolerance regime instead
            dcop = generate_graph_coloring(
                nv, 3, "scalefree", m_edge=2, soft=True,
                noise_level=0.0, seed=nv)
            with open(os.path.join(work, f"i{topo}.yaml"), "w") as f:
                f.write(dcop_yaml(dcop))
            topo += 1
        bench_yaml = os.path.join(work, "bench.yaml")
        with open(bench_yaml, "w") as f:
            f.write(f"""
sets:
  s1:
    path: '{work}/i*.yaml'
    iterations: {iterations}
batches:
  campaign:
    command: solve
    command_options:
      algo: [dsa]
      max_cycles: 30
""")
        n_jobs = topo * iterations
        campaign = {}
        assignments = {}
        for prec in ("f32", "bf16"):
            out_dir = os.path.join(work, f"out_{prec}")
            t0 = time.perf_counter()
            proc = subprocess.run(
                [sys.executable, "-m", "pydcop_tpu.dcop_cli", "batch",
                 bench_yaml, "--fuse-hetero", "--precision", prec,
                 "--dir", out_dir],
                capture_output=True, text=True, timeout=1200, env=env,
                cwd=repo)
            elapsed = time.perf_counter() - t0
            if proc.returncode != 0:
                raise RuntimeError(
                    f"{prec} campaign rc={proc.returncode}: "
                    f"{proc.stderr[-300:]}")
            rows = {}
            for path in glob.glob(os.path.join(out_dir, "*.json")):
                with open(path) as f:
                    r = _json.load(f)
                rows[os.path.basename(path)] = (
                    r["assignment"], r["cycle"], r["cost"])
                if r.get("precision") != prec:
                    raise RuntimeError(
                        f"{prec} campaign result missing its "
                        "precision field")
            if len(rows) != n_jobs:
                raise RuntimeError(
                    f"{prec} campaign wrote {len(rows)} results, "
                    f"expected {n_jobs}")
            campaign[prec] = round(n_jobs / elapsed, 1)
            assignments[prec] = rows
        if assignments["f32"] != assignments["bf16"]:
            diff = sum(1 for k in assignments["f32"]
                       if assignments["f32"][k]
                       != assignments["bf16"][k])
            raise RuntimeError(
                f"precision contract violated: {diff}/{n_jobs} fused "
                "campaign jobs diverged between f32 and bf16")
        return {
            "metric": f"precision_ab_{n}var_mesh_and_"
                      f"{n_jobs}job_campaign",
            "value": {
                "mesh_ms_per_cycle": {
                    p: round(mesh_out[p]["ms_per_cycle"], 3)
                    for p in mesh_out},
                "campaign_instances_per_sec": campaign,
            },
            "unit": "ms/cycle + instances/s",
            "step_bytes_accessed": {
                "f32": bytes_f32, "bf16": bytes_bf16,
                "reduction": round(1 - bytes_bf16 / bytes_f32, 3)},
            "step_bytes_accessed_arity3": {
                "f32": bytes3_f32, "bf16": bytes3_bf16,
                "reduction": round(1 - bytes3_bf16 / bytes3_f32, 3)},
            "selections_equal": True,
            "campaign_jobs": n_jobs,
            "hardware": "cpu-host",
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


_TELEMETRY_CHILD = r"""
import hashlib, json, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from pydcop_tpu.generators.fast import coloring_factor_arrays
from pydcop_tpu.parallel import make_mesh
from pydcop_tpu.parallel.sharded_maxsum import ShardedMaxSum

N, CYCLES, REPS = {n}, {cycles}, {reps}
# the round-5/7 mesh shape: 10k vars / 30k edges / 3 colors, 4
# instances on the dp axis of the (4, 2) mesh, at the solvers'
# DEFAULT configuration (stability=0.1): the delta-convergence reduce
# already runs every cycle, so the residual plane reads the step's
# own delta for free and the A/B isolates telemetry's real increment
# (flips + conflict evaluator + plane writes).  noise=0.05 keeps the
# message planes busy and convergence past the CYCLES budget;
# bit-exactness makes cycles_run identical across legs either way.
# Both legs live in THIS process and interleave, so host-load drift
# hits both equally (the naive one-leg-per-process protocol measured
# 10%+ apparent overheads that were pure scheduling noise)
arrays = coloring_factor_arrays(N, 3 * N, 3, seed=17, noise=0.05)
legs = {{}}
for telemetry in (False, True):
    sm = ShardedMaxSum(arrays, make_mesh(8), damping=0.5,
                       stability=0.1, batch=4)
    sm.run(2, chunk_size=32, collect_metrics=telemetry)  # warm-up
    legs[telemetry] = sm
times = {{False: [], True: []}}
out = {{}}
for _ in range(REPS):
    for telemetry, sm in legs.items():
        t0 = time.perf_counter()
        sel, cycles = sm.run(CYCLES, chunk_size=32,
                             collect_metrics=telemetry)
        times[telemetry].append(time.perf_counter() - t0)
        out[telemetry] = {{
            "ms_per_cycle": min(times[telemetry]) * 1e3 / cycles,
            "records": len(sm.last_cycle_metrics),
            "host_syncs": sm.last_run_stats["host_syncs"],
            "sel_sha": hashlib.sha256(
                np.ascontiguousarray(np.asarray(sel, dtype=np.int32))
                .tobytes()).hexdigest()}}
# paired per-rep ratios: legs alternate back-to-back, so host-load
# drift cancels within a pair; the median pair and the best-of-N
# ratio are BOTH honest aggregates, and a shared noisy host can push
# either one high on its own — a real regression shows in both, so
# the contract reads the smaller (a 6% phantom from one busy minute
# must not fail the suite; a real >5% regression still does)
ratios = sorted(on / off for off, on
                in zip(times[False], times[True]))
out[True]["paired_overhead"] = min(
    ratios[len(ratios) // 2],
    min(times[True]) / min(times[False])) - 1.0
print("CHILD_RESULT " + json.dumps({{"off": out[False],
                                     "on": out[True]}}))
"""


def bench_telemetry_overhead(quick=False):
    """Telemetry off/on A/B (ISSUE 5): the SAME 10k-var sharded
    MaxSum program, default solver configuration, with and without
    the on-device metric planes (residual/flips/conflicts written
    inside the chunk body, drained at chunk boundaries only).

    One child process holds BOTH legs and interleaves them
    (best-of-6): one-leg-per-process A/Bs on a shared host measured
    10%+ apparent overheads that were scheduling drift, not
    telemetry.  THREE contracts asserted IN the bench: selections
    bit-identical (telemetry must never perturb the solve), zero
    extra host syncs, and ms/cycle overhead under 5%.  Host-CPU
    numbers, labeled as such per the round-4 protocol."""
    import json as _json
    import os
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    n = 1024 if quick else 10_000
    cycles = 30
    proc = subprocess.run(
        [sys.executable, "-c", _TELEMETRY_CHILD.format(
            n=n, cycles=cycles, reps=8)],
        capture_output=True, text=True, timeout=1800, env=env,
        cwd=repo)
    out = None
    for line in proc.stdout.splitlines():
        if line.startswith("CHILD_RESULT "):
            out = _json.loads(line[len("CHILD_RESULT "):])
    if out is None:
        raise RuntimeError(
            (proc.stderr.strip().splitlines()
             or ["no output"])[-1][:300])
    if out["on"]["sel_sha"] != out["off"]["sel_sha"]:
        raise RuntimeError(
            "telemetry contract violated: telemetry-on selections "
            "diverged from telemetry-off")
    if out["on"]["records"] != cycles:
        raise RuntimeError(
            f"telemetry contract violated: {out['on']['records']} "
            f"cycle records for {cycles} cycles")
    if out["on"]["host_syncs"] != out["off"]["host_syncs"]:
        raise RuntimeError(
            "telemetry contract violated: extra host syncs "
            f"({out['on']['host_syncs']} vs "
            f"{out['off']['host_syncs']})")
    overhead = out["on"]["paired_overhead"]
    # the < 5% budget is a claim about the production shape, where the
    # step amortizes the evaluator's fixed per-cycle collective costs;
    # at --quick's 1k vars the step is so cheap that those fixed costs
    # dominate the RATIO while being identical in absolute terms — the
    # quick run smoke-tests the machinery, the full run asserts
    if overhead >= 0.05 and not quick:
        raise RuntimeError(
            f"telemetry contract violated: {overhead:.1%} ms/cycle "
            "overhead (budget < 5%)")
    return {
        "metric": f"telemetry_overhead_{n}var_ms_per_cycle",
        "value": {
            "off": round(out["off"]["ms_per_cycle"], 3),
            "on": round(out["on"]["ms_per_cycle"], 3),
            "overhead": round(overhead, 4),
        },
        "unit": "ms/cycle",
        "cycles": cycles,
        "selections_equal": True,
        "sync_contract_ok": True,
        "overhead_contract_asserted": not quick,
        "hardware": "cpu-host",
        "virtual_mesh": True,
    }


def bench_decimation(quick=False):
    """Decimated Max-Sum A/B (ISSUE 6) on the loopy 10k-var coloring
    mesh (the bench.py instance shape, where plain Max-Sum sits at
    ~15% conflict rate and never settles): plain vs
    ``decimation_p=0.25, decimation_every=4``, same seed, whole
    horizon in ONE jitted fori_loop per leg (zero mid-run host
    syncs).  Cycles-to-convergence is the last cycle the decoded
    selection CHANGED — the honest measure on an instance where
    message quiescence never happens.  TWO contracts asserted IN the
    bench: the decimated leg settles strictly earlier than plain
    (which must still be changing at the horizon — otherwise the
    instance stopped being a regression witness), and its final
    conflict rate is strictly lower.  Host-CPU numbers, labeled."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pydcop_tpu.algorithms.maxsum import MaxSumLaneSolver
    from pydcop_tpu.generators.fast import coloring_factor_arrays

    n = 1024 if quick else 10_000
    e = 3 * n
    horizon = 96 if quick else 256
    arrays = coloring_factor_arrays(n, e, 3, seed=7, noise=0.05)
    b = arrays.buckets[0]
    u = jnp.asarray(b.var_ids[:, 0])
    v = jnp.asarray(b.var_ids[:, 1])

    def leg(solver):
        def body(i, carry):
            s, prev, last = carry
            s = solver.step(s)
            sel = solver.assignment_indices(s)
            last = jnp.where(jnp.any(sel != prev), i + 1, last)
            return s, sel, last

        @jax.jit
        def run(s):
            sel0 = solver.assignment_indices(s)
            s2, sel, last = jax.lax.fori_loop(
                0, horizon, body, (s, sel0, jnp.int32(0)))
            conf = jnp.sum(sel[u] == sel[v]).astype(jnp.int32)
            return sel, last, conf

        s0 = solver.init_state(jax.random.PRNGKey(0))
        _, last, conf = run(s0)  # warm-up/compile included: one shot
        t0 = time.perf_counter()
        _, last, conf = run(solver.init_state(jax.random.PRNGKey(0)))
        jax.block_until_ready(conf)
        dt = time.perf_counter() - t0
        return int(last), int(conf), dt

    kw = dict(damping=0.5, stability=0.0)
    plain_last, plain_conf, plain_s = leg(MaxSumLaneSolver(
        arrays, **kw))
    dec_last, dec_conf, dec_s = leg(MaxSumLaneSolver(
        arrays, decimation_p=0.25, decimation_every=4, **kw))
    if plain_last < horizon - 8:
        raise RuntimeError(
            f"decimation contract witness lost: plain Max-Sum settled "
            f"at cycle {plain_last}/{horizon} — the instance is no "
            f"longer loopy enough to regress against")
    if dec_last >= plain_last:
        raise RuntimeError(
            f"decimation contract violated: decimated run settled at "
            f"cycle {dec_last}, plain at {plain_last} (want strictly "
            f"fewer cycles-to-convergence)")
    if dec_conf >= plain_conf:
        raise RuntimeError(
            f"decimation contract violated: decimated final conflicts "
            f"{dec_conf} >= plain {plain_conf}")
    return {
        "metric": f"decimation_ab_{n}var_coloring",
        "value": {
            "plain": {"last_change_cycle": plain_last,
                      "conflicts": plain_conf,
                      "conflict_rate": round(plain_conf / e, 5),
                      "seconds": round(plain_s, 3)},
            "decimated": {"last_change_cycle": dec_last,
                          "conflicts": dec_conf,
                          "conflict_rate": round(dec_conf / e, 5),
                          "seconds": round(dec_s, 3),
                          "p": 0.25, "every": 4},
        },
        "unit": "cycles",
        "horizon": horizon,
        "contracts_asserted": True,
        "hardware": jax.default_backend(),
    }


def bench_bnb_pruning(quick=False):
    """Branch-and-bound pruned-reduction A/B (ISSUE 6) on the two
    marquee n-ary families.  PEAV meeting scheduling with k-ary
    event-equality factors is the bound-friendly shape (a few cheap
    diagonal cells, a high penalty plateau everywhere else): the
    asserted leg — selections BIT-EXACT with the full scan and a
    >= 30% mean pruned-cell fraction.  SECP rules are the
    bound-hostile shape (smooth utility cubes, weak per-slot bounds):
    reported, not asserted, so the trade stays visible.  ms/cycle is
    host-CPU (a sequential while_loop sweep vs one fused full scan —
    the chip trade differs), labeled per the round-4 protocol."""
    import jax
    import numpy as np

    from pydcop_tpu.algorithms.maxsum import MaxSumSolver
    from pydcop_tpu.dcop.dcop import filter_dcop
    from pydcop_tpu.generators.meetingscheduling import \
        generate_meetings
    from pydcop_tpu.generators.secp import generate_secp
    from pydcop_tpu.graphs.arrays import FactorGraphArrays

    cycles = 10 if quick else 30

    def ab(arrays):
        def leg(solver):
            # untimed pass: compile AND collect the per-cycle pruned
            # fractions here — a float(s["pruned"]) host sync inside
            # the timed loop would bias the bnb leg's ms/cycle upward
            # vs the full scan, which never pays that round-trip
            step = jax.jit(solver.step)
            s = solver.init_state(jax.random.PRNGKey(0))
            fr = []
            for _ in range(cycles):
                s = step(s)
                if "pruned" in s:
                    fr.append(float(s["pruned"]))
            sel = np.asarray(solver.assignment_indices(s))
            # timed pass: steps only, one block at the end
            s = solver.init_state(jax.random.PRNGKey(0))
            jax.block_until_ready(s["q"])
            t0 = time.perf_counter()
            for _ in range(cycles):
                s = step(s)
            jax.block_until_ready(s["q"])
            ms = (time.perf_counter() - t0) / cycles * 1000
            return sel, ms, (float(np.mean(fr)) if fr else None)

        sel_f, ms_f, _ = leg(MaxSumSolver(arrays, damping=0.5))
        sel_b, ms_b, pruned = leg(MaxSumSolver(arrays, damping=0.5,
                                               bnb=True))
        if not np.array_equal(sel_f, sel_b):
            raise RuntimeError(
                "bnb contract violated: pruned selections diverged "
                "from the full scan")
        return {"ms_per_cycle_full": round(ms_f, 3),
                "ms_per_cycle_bnb": round(ms_b, 3),
                # None = no bucket cleared the plan gates (all cubes
                # under BNB_MIN_CELLS or arity < 3): nothing to prune
                "pruned_fraction": None if pruned is None
                else round(pruned, 4),
                "selections_equal": True}

    peav = filter_dcop(generate_meetings(
        slots_count=8, events_count=20 if quick else 80,
        resources_count=16 if quick else 60, max_resources_event=3,
        seed=13, nary_equalities=True))
    secp = filter_dcop(generate_secp(
        lights_count=20 if quick else 60,
        models_count=12 if quick else 40,
        rules_count=10 if quick else 30, seed=7))
    out = {
        "peav_nary": ab(FactorGraphArrays.build(peav,
                                                arity_sorted=True)),
        "secp": ab(FactorGraphArrays.build(secp, arity_sorted=True)),
    }
    # the asserted contract: the bound-friendly workload must prune
    # at least 30% of its plannable cells at full parity
    frac = out["peav_nary"]["pruned_fraction"]
    if frac is None or frac < 0.30:
        raise RuntimeError(
            f"bnb contract violated: PEAV pruned-cell fraction "
            f"{frac if frac is None else format(frac, '.1%')} < 30% "
            f"(None = no bucket built a plan)")
    return {
        "metric": "bnb_pruning_ab_nary",
        "value": out,
        "unit": "pruned-cell fraction",
        "cycles": cycles,
        "contracts_asserted": True,
        "hardware": jax.default_backend(),
    }


def _tree_factor_arrays(n, span, seed, D=3):
    """A weighted random tree (parent of node i drawn from the
    preceding ``span`` nodes): min-sum CONVERGES on trees, so this is
    the settling warm-traffic shape conditional Max-Sum targets — a
    converged base plus local perturbations that re-settle in tens of
    cycles.  Canonical factor-major edge layout, like the fast
    generators."""
    import numpy as np

    from pydcop_tpu.graphs.arrays import (FactorBucket,
                                          FactorGraphArrays)

    rng = np.random.default_rng(seed)
    parent = np.maximum(
        0, np.arange(1, n) - rng.integers(1, span, size=n - 1))
    edges = np.stack([parent, np.arange(1, n)],
                     axis=1).astype(np.int32)
    F = n - 1
    bucket = FactorBucket(
        arity=2, factor_ids=np.arange(F, dtype=np.int32),
        cubes=rng.integers(0, 9, size=(F, D, D)).astype(np.float32),
        edge_ids=np.arange(2 * F, dtype=np.int32).reshape(F, 2),
        var_ids=edges.copy())
    return FactorGraphArrays(
        n_vars=n, n_factors=F, n_edges=2 * F, max_domain=D,
        sign=1.0,
        var_names=[f"v{i}" for i in range(n)],
        factor_names=[f"c{i}" for i in range(F)],
        domain_size=np.full(n, D, dtype=np.int32),
        domain_mask=np.ones((n, D), dtype=bool),
        var_costs=rng.uniform(0, 0.05, size=(n, D)).astype(
            np.float32),
        edge_var=edges.reshape(-1).astype(np.int32),
        edge_factor=np.repeat(np.arange(F, dtype=np.int32), 2),
        buckets=[bucket])


def bench_dynamic(quick=False):
    """Dynamic-DCOP A/B (ISSUE 10 + 12 + 14): a 20-event scenario
    over a 10k-var coloring mesh, three legs solving identical
    problems —

    * **resident** (ISSUE 12, the default): instance planes stay on
      device, ``apply`` is a compiled donated scatter, per-event
      upload is O(touched rows);
    * **reupload** (the PR 10 baseline): host-plane edits + full
      ``jnp.asarray`` re-materialization per event;
    * **cold**: a fresh solver + engine per perturbed instance (the
      pre-dynamics workflow).

    Contracts asserted: after the first solve every warm dispatch on
    BOTH warm legs shows ZERO ``compile_s``/``trace_lower_s`` spans
    (the scatter's one-off compiles ride the distinct ``apply_*``
    names); the resident leg's per-event ``upload_bytes`` is >= 10x
    below the reupload leg's; and the resident leg's per-event
    overhead beyond pure execute is no worse than the reupload
    leg's.  Host-CPU numbers, honestly labeled: at this size the
    48-cycle execution dominates ms/event, so the end-to-end ratio
    is reported, not asserted.

    ISSUE 14 adds two leg sets:

    * **layout ladder** — edge_major vs lane_major vs fused, each
      under the fixed AND adaptive budget schedule, on a cost-edit
      stream with ``carry='reset'`` (the structurally cold-exact
      mode): selections AND convergence cycles must agree
      bit-for-bit across all six legs, every warm dispatch
      retrace-free.  Like-for-like per-event times are reported
      (host CPU: the fused cycle is ~2x the edge-major one; the
      lane layout is a TPU-tile bet and roughly breaks even here);
    * **settling warm traffic** — a 10k-var weighted random tree
      (min-sum converges; local cost edits re-settle in tens of
      cycles).  The headline contract compares the new warm path
      (fused + adaptive budget) on this stream against the PR 12
      configuration (edge-major, fixed ``chunk_size`` budget) on
      the mesh stream above, where every event burns the full
      compiled budget because the 10k loopy mesh never meets the
      stability rule: >= 3x fewer ms per warm event, asserted in
      full mode.  The decomposition (layout ~2x, the rest from
      stopping at the settle boundary instead of running the fixed
      budget) is reported in the same result block, so the two
      streams are never conflated."""
    import jax
    import numpy as np

    from pydcop_tpu.algorithms.maxsum import MaxSumSolver
    from pydcop_tpu.dynamics import DynamicEngine
    from pydcop_tpu.engine.sync_engine import SyncEngine
    from pydcop_tpu.generators.fast import coloring_factor_arrays

    n = 1024 if quick else 10_000
    e = 3 * n
    n_events = 8 if quick else 20
    max_cycles = 24 if quick else 48
    arrays = coloring_factor_arrays(n, e, 3, seed=7)

    def make_events(rng):
        """The 20-event mix over factor names c0..c{e-1}: mostly cost
        updates, every 4th event an add+remove pair (edit capacity
        from the reserve)."""
        events = []
        for i in range(n_events):
            if i % 4 == 3:
                u, v = rng.randint(0, n, size=2)
                events.append([
                    {"type": "add_constraint", "name": f"dyn{i}",
                     "scope": [arrays.var_names[u],
                               arrays.var_names[v if v != u
                                                else (u + 1) % n]],
                     "costs": rng.randint(0, 9,
                                          size=(3, 3)).tolist()},
                ] + ([{"type": "remove_constraint",
                       "name": f"dyn{i - 4}"}] if i >= 7 else []))
            else:
                picks = rng.randint(0, e, size=4)
                events.append([
                    {"type": "change_costs", "name": f"c{int(f)}",
                     "costs": rng.randint(0, 9,
                                          size=(3, 3)).tolist()}
                    for f in picks])
        return events

    def warm_leg(resident):
        """One warm engine over the (identical) event stream; returns
        wall, execute and upload totals.  Pinned to the PR 12
        configuration (edge-major, fixed budget) — this IS the
        baseline the ISSUE 14 headline below is measured against."""
        eng = DynamicEngine(arrays, reserve="vars:8,2:32",
                            chunk_size=max_cycles,
                            resident=resident,
                            layout="edge_major",
                            warm_budget="fixed")
        t0 = time.perf_counter()
        r0 = eng.solve(max_cycles=max_cycles)
        first_s = time.perf_counter() - t0
        assert "trace_lower_s" in r0["spans"] or \
            "deserialize_s" in r0["spans"]
        events = make_events(np.random.RandomState(11))
        t0 = time.perf_counter()
        exec_s = 0.0
        scatter_compile_s = 0.0
        upload = []
        for ev in events:
            eng.apply(ev)
            r = eng.solve(max_cycles=max_cycles)
            if "compile_s" in r["spans"] or \
                    "trace_lower_s" in r["spans"]:
                raise RuntimeError(
                    f"warm contract violated: re-solve spans "
                    f"{r['spans']} carry a trace/compile after the "
                    f"first solve")
            if not r["warm_start"]:
                raise RuntimeError("warm contract violated: dispatch "
                                   "not marked warm_start")
            exec_s += r["spans"].get("execute_s", 0.0)
            # one-off scatter-shape compiles are startup cost, kept
            # out of the steady-state overhead (same discipline as
            # compile_s never landing in a job's `time`) — reported
            scatter_compile_s += (
                r["spans"].get("apply_trace_lower_s", 0.0)
                + r["spans"].get("apply_compile_s", 0.0))
            upload.append(r["upload_bytes"])
        wall = time.perf_counter() - t0
        return {"first_s": first_s, "wall_s": wall,
                "exec_s": exec_s,
                "scatter_compile_s": scatter_compile_s,
                "upload_bytes_per_event": int(np.mean(upload))}

    res = warm_leg(resident=True)
    reup = warm_leg(resident=False)

    # ---- cold leg: a fresh solver + engine per perturbed instance
    # (the same edited planes, so all legs solve identical problems)
    eng2 = DynamicEngine(arrays, reserve="vars:8,2:32")
    cold_s = 0.0
    for ev in make_events(np.random.RandomState(11)):
        eng2.apply(ev)
        snap = eng2.instance.snapshot_arrays()
        t0 = time.perf_counter()
        solver = MaxSumSolver(snap)
        engine = SyncEngine(solver, chunk_size=max_cycles)
        engine.run(max_cycles=max_cycles)
        cold_s += time.perf_counter() - t0

    # the upload contract: resident transfers O(touched rows), the
    # re-upload baseline re-materializes every plane
    up_ratio = reup["upload_bytes_per_event"] / max(
        res["upload_bytes_per_event"], 1)
    if up_ratio < 10:
        raise RuntimeError(
            f"resident contract violated: upload_bytes only "
            f"{up_ratio:.1f}x below the re-upload baseline "
            f"({res['upload_bytes_per_event']} vs "
            f"{reup['upload_bytes_per_event']} B/event)")
    # the overhead contract: steady-state per-event cost beyond pure
    # execute (the apply + upload + reset tax the scatter eliminates)
    # must not regress; the one-off scatter-shape compiles are
    # startup, reported separately; 1 ms tolerance absorbs host-CPU
    # scheduler noise
    res_ovh = 1000 * (res["wall_s"] - res["exec_s"]
                      - res["scatter_compile_s"]) / n_events
    reup_ovh = 1000 * (reup["wall_s"] - reup["exec_s"]
                       - reup["scatter_compile_s"]) / n_events
    if res_ovh > reup_ovh + 1.0:
        raise RuntimeError(
            f"resident contract violated: per-event overhead "
            f"{res_ovh:.2f} ms > re-upload baseline "
            f"{reup_ovh:.2f} ms")

    # ---- ISSUE 14 leg set 1: the mesh timing ladder ---------------
    # like-for-like per-cycle cost per layout on the PR 12 stream
    # (cost edits only; every leg runs the full budget — the loopy
    # mesh never meets the stability rule).  Timing only: selections
    # truncated mid-oscillation on a tie-heavy uniform mesh are not
    # association-robust across layouts, so the bit-exactness oracle
    # lives on the CONVERGING stream below, where margins protect
    # every argmin
    rng = np.random.RandomState(23)
    mesh_events = [
        [{"type": "change_costs", "name": f"c{int(f)}",
          "costs": rng.randint(0, 9, size=(3, 3)).tolist()}
         for f in rng.randint(0, e, size=4)]
        for _ in range(n_events)]

    def layout_leg(instance, events, layout, warm_budget, reserve,
                   budget_cycles, assert_finished=False):
        eng = DynamicEngine(instance, reserve=reserve,
                            chunk_size=max_cycles, carry="reset",
                            layout=layout, warm_budget=warm_budget,
                            max_cycles=budget_cycles)
        eng.solve()
        sigs, cycles, settles = [], [], []
        t0 = time.perf_counter()
        for ev in events:
            eng.apply(ev)
            r = eng.solve()
            if "compile_s" in r["spans"] or \
                    "trace_lower_s" in r["spans"]:
                raise RuntimeError(
                    f"{layout}/{warm_budget} warm contract "
                    f"violated: {r['spans']}")
            if assert_finished and r["status"] != "FINISHED":
                raise RuntimeError(
                    f"settle-stream event did not settle under "
                    f"{layout}/{warm_budget} (cycle {r['cycle']})")
            sigs.append(hash(tuple(sorted(r["assignment"].items()))))
            cycles.append(r["cycle"])
            settles.append(r["settle_chunk"])
        wall = time.perf_counter() - t0
        eng.close()
        return {"ms_per_event": round(1000 * wall / len(events), 2),
                "sigs": sigs, "cycles": cycles, "settles": settles}

    mesh_ladder = {
        lay: layout_leg(arrays, mesh_events, lay, "fixed",
                        "vars:8,2:32", max_cycles)
        for lay in ("edge_major", "lane_major", "fused")}

    # ---- ISSUE 14 leg set 2: settling warm traffic + the six-leg
    # bit-exactness ladder ------------------------------------------
    # the conditional-Max-Sum serving shape: a converged base plus
    # local cost edits that re-settle in tens of cycles — the stream
    # where stopping at the settle boundary (instead of burning the
    # fixed compiled budget the mesh stream forces) pays.  Converged
    # selections carry real margins, so here the oracle is strict:
    # selections AND cycles bit-for-bit across all six
    # (layout, budget) legs
    tree = _tree_factor_arrays(n, span=100, seed=7)
    rng = np.random.RandomState(31)
    tree_events = [
        [{"type": "change_costs", "name": f"c{int(f)}",
          "costs": rng.randint(0, 9, size=(3, 3)).tolist()}
         for f in rng.randint(0, n - 1, size=4)]
        for _ in range(n_events)]
    tree_budget = 200 if quick else 400

    ladder = {f"{lay}/{bud}": layout_leg(
        tree, tree_events, lay, bud, "2:32", tree_budget,
        assert_finished=True)
        for lay in ("edge_major", "lane_major", "fused")
        for bud in ("fixed", "adaptive")}
    ref_leg = ladder["edge_major/fixed"]
    for tag, lg in ladder.items():
        if lg["sigs"] != ref_leg["sigs"] \
                or lg["cycles"] != ref_leg["cycles"]:
            raise RuntimeError(
                f"layout ladder contract violated: {tag} "
                f"selections/cycles differ from edge_major/fixed")

    settle_new = ladder["fused/adaptive"]
    if any(s is None for s in settle_new["settles"]):
        raise RuntimeError(
            "settle telemetry contract violated: a FINISHED warm "
            "event reported no settle_chunk")

    # steady state = wall minus the one-off scatter-shape compiles
    # (startup, like any compile span); both reported
    warm_s = res["wall_s"] - res["scatter_compile_s"]
    reup_s = reup["wall_s"] - reup["scatter_compile_s"]

    # the ISSUE 14 headline: ms per warm event, new path (fused +
    # adaptive, settling stream) vs the PR 12 configuration
    # (edge-major, fixed budget, the mesh stream where every event
    # burns the full compiled budget).  Cross-stream by construction
    # — the like-for-like decomposition rides alongside so the two
    # are never conflated
    pr12_ms = 1000 * warm_s / n_events
    warm_speedup = pr12_ms / max(settle_new["ms_per_event"], 1e-9)
    like_for_like = (mesh_ladder["edge_major"]["ms_per_event"]
                     / max(mesh_ladder["fused"]["ms_per_event"],
                           1e-9))
    if not quick and warm_speedup < 3.0:
        raise RuntimeError(
            f"warm-path contract violated: fused+adaptive settling "
            f"events at {settle_new['ms_per_event']:.1f} ms/event "
            f"is only {warm_speedup:.2f}x under the PR 12 "
            f"edge-major fixed-budget baseline ({pr12_ms:.1f} "
            f"ms/event)")

    return {
        "metric": f"dynamic_scenario_{n}var_{n_events}events",
        "value": {
            "first_solve_s": round(res["first_s"], 3),
            "warm_per_event_ms": round(1000 * warm_s / n_events, 2),
            "warm_wall_s": round(res["wall_s"], 3),
            "warm_reupload_per_event_ms": round(
                1000 * reup_s / n_events, 2),
            "warm_overhead_per_event_ms": round(res_ovh, 2),
            "reupload_overhead_per_event_ms": round(reup_ovh, 2),
            "upload_bytes_per_event": res["upload_bytes_per_event"],
            "reupload_bytes_per_event":
                reup["upload_bytes_per_event"],
            "upload_reduction": round(up_ratio, 1),
            "scatter_compile_s": round(res["scatter_compile_s"], 3),
            "cold_per_event_s": round(cold_s / n_events, 3),
            "speedup_vs_cold": round(
                cold_s / max(warm_s, 1e-9), 1),
            "speedup_vs_reupload": round(
                reup_s / max(warm_s, 1e-9), 2),
            # ISSUE 14: like-for-like per-layout timing on the mesh
            # stream (every leg runs the full budget)
            "mesh_ladder_ms_per_event": {
                tag: lg["ms_per_event"]
                for tag, lg in mesh_ladder.items()},
            "like_for_like_fused_speedup": round(like_for_like, 2),
            # ISSUE 14: settling warm traffic (weighted tree): the
            # six-leg (layout x budget) ladder, selections AND
            # cycles asserted bit-exact vs edge_major/fixed
            "settle_ladder_ms_per_event": {
                tag: lg["ms_per_event"]
                for tag, lg in ladder.items()},
            "settle_fused_adaptive": {
                "ms_per_event": settle_new["ms_per_event"],
                "mean_settle_cycles": round(float(np.mean(
                    settle_new["cycles"])), 1),
                "settle_chunks": settle_new["settles"]},
            "pr12_baseline_ms_per_event": round(pr12_ms, 2),
            "warm_speedup_vs_pr12_fixed": round(warm_speedup, 2),
        },
        "unit": "seconds",
        "events": n_events,
        "max_cycles": max_cycles,
        "contracts_asserted": True,  # zero trace/compile + upload/ovh
        # + layout-ladder selections/cycles bit-exactness + settle
        # telemetry + (full mode) the >=3x warm headline
        "hardware": jax.default_backend(),
    }


def bench_roi(quick=False):
    """Region-of-interest warm solves (ISSUE 16): the activity-gated
    ladder over perturbation sizes x graph sizes, on the settling
    warm-traffic shape (the ``_tree_factor_arrays`` weighted tree —
    min-sum converges, so local edits re-settle and the residual gate
    has a fixed point to settle TO).  Each rung runs the same event
    stream through two fused+adaptive engines — ``roi=True`` and the
    PR 14 full-sweep baseline — timing apply+solve per event after a
    warmup that absorbs the one-off window-capacity-rung compiles
    (window programs compile per pow2 capacity, exactly like scatter
    shapes).

    Asserted, not eyeballed:

    * every warm dispatch on BOTH engines is retrace-free (bare
      ``trace_lower_s``/``compile_s`` absent; the ROI programs ride
      the distinct ``roi_*`` span names);
    * the activity gate ENGAGES on every warm event of this stream
      (no full-sweep fallbacks: active_fraction < 1);
    * the settled-region oracle: rows the ROI engine never activated
      (across ALL events so far) hold the shared base fixed point's
      selections bit-exactly — the union-of-windows is the only
      place the masked sweeps may move a selection.  (The anchor is
      the base solve both engines share, not the live full-sweep
      leg: a full sweep is free to drift near-tied rows far from
      the edit by sub-threshold residuals, which is exactly the
      work ROI declines to redo.)  The quality gap vs the live
      full sweep is reported per rung, not asserted;
    * full mode, 10k vars: small edits (<= 8 touched rows) run
      >= 5x faster per event than the full-sweep baseline — the
      ISSUE 16 acceptance headline;
    * full mode, 100k vars: small edits land at single-digit
      ms/event.

    ``active_fraction`` is emitted alongside every ms/event figure so
    the O(touched-region) claim is inspectable, not inferred.
    Host-CPU numbers, honestly labeled."""
    import jax
    import numpy as np

    from pydcop_tpu.dynamics import DynamicEngine

    def leg(tree, n, edit_rows, n_events, warmup, budget, seed):
        """One ladder rung: identical events through the ROI engine
        and the full-sweep oracle, per-event apply+solve wall on
        each, settled-region bit-exactness after every event."""
        rng = np.random.RandomState(seed)
        events = [
            [{"type": "change_costs", "name": f"c{int(f)}",
              "costs": rng.randint(0, 9, size=(3, 3)).tolist()}
             for f in rng.randint(0, n - 1, size=edit_rows)]
            for _ in range(n_events + warmup)]
        def mk(roi):
            return DynamicEngine(tree, reserve="2:32",
                                 max_cycles=budget, layout="fused",
                                 warm_budget="adaptive", roi=roi)

        roi_eng, oracle = mk(True), mk(False)
        base = []
        for eng in (roi_eng, oracle):
            r0 = eng.solve()
            if r0["status"] != "FINISHED":
                raise RuntimeError(
                    f"roi bench base solve did not converge at n={n}"
                    f" within {budget} cycles; the settling-stream "
                    f"premise is broken")
            base.append(r0["assignment"])
        if base[0] != base[1]:
            raise RuntimeError(
                "roi bench: the two engines' base solves disagree; "
                "no shared fixed point to anchor the settled-region "
                "oracle")
        base_asg = base[0]
        # sized to the engine's padded rung (reserve rows included),
        # not the logical n — live rows are a prefix of it
        ever_union = None
        roi_ms, base_ms, afs, hops = [], [], [], 0
        cost_gap = []
        for i, ev in enumerate(events):
            t0 = time.perf_counter()
            roi_eng.apply(ev)
            r = roi_eng.solve()
            dt = 1000 * (time.perf_counter() - t0)
            t0 = time.perf_counter()
            oracle.apply(ev)
            ro = oracle.solve()
            dto = 1000 * (time.perf_counter() - t0)
            for tag, rr in (("roi", r), ("full-sweep", ro)):
                if "compile_s" in rr["spans"] \
                        or "trace_lower_s" in rr["spans"]:
                    raise RuntimeError(
                        f"{tag} warm contract violated at event {i}: "
                        f"{rr['spans']}")
            af = r["active_fraction"]
            if af >= 1.0 or roi_eng._roi_ever_active is None:
                raise RuntimeError(
                    f"roi gate fell back to a full sweep on the "
                    f"settling stream (event {i}, edit_rows="
                    f"{edit_rows}, status {r['status']}); event cost "
                    f"is O(|V|) again")
            ever = roi_eng._roi_ever_active
            ever_union = (ever.copy() if ever_union is None
                          else ever_union | ever)
            asg = r["assignment"]
            leaked = [k for k, v in base_asg.items()
                      if asg[k] != v and not ever_union[int(k[1:])]]
            if leaked:
                raise RuntimeError(
                    f"settled-region contract violated at event {i}: "
                    f"rows {sorted(leaked)[:8]} left the shared base "
                    f"fixed point but were never activated")
            if i >= warmup:
                roi_ms.append(dt)
                base_ms.append(dto)
                afs.append(af)
                hops += r["frontier_expansions"]
                cost_gap.append(r["cost"] - ro["cost"])
        roi_eng.close()
        oracle.close()
        med = float(np.median(roi_ms))
        med_base = float(np.median(base_ms))
        return {
            "ms_per_event": round(med, 3),
            "baseline_ms_per_event": round(med_base, 3),
            "speedup": round(med_base / max(med, 1e-9), 2),
            "active_fraction": round(float(np.mean(afs)), 6),
            "frontier_expansions": int(hops),
            "mean_cost_gap_vs_full_sweep": round(
                float(np.mean(cost_gap)), 4),
        }

    n = 2_000 if quick else 10_000
    # the tree settles in < 40 cycles; the adaptive warm schedule
    # scales its chunk ladder with the budget, so an oversized budget
    # inflates BOTH legs' per-event execute for no extra convergence
    budget = 400
    edit_sizes = (1, 8) if quick else (1, 8, 64)
    n_events = 5 if quick else 12
    warmup = 4
    tree = _tree_factor_arrays(n, span=100, seed=7)
    ladder = {}
    for k in edit_sizes:
        rung = leg(tree, n, k, n_events, warmup, budget, seed=40 + k)
        ladder[f"edit_{k}"] = rung
        # the acceptance headline (full mode only: quick's 2k-var
        # rung is host-scheduler noise at these absolute times)
        if not quick and k <= 8 and rung["speedup"] < 5.0:
            raise RuntimeError(
                f"roi contract violated: {k}-row edits at {n} vars "
                f"ran {rung['ms_per_event']} ms/event, only "
                f"{rung['speedup']}x under the full-sweep baseline "
                f"({rung['baseline_ms_per_event']} ms/event); "
                f"ISSUE 16 requires >= 5x")

    value = {"vars": n, "events_per_rung": n_events,
             "ladder": ladder}
    if not quick:
        # the 100k-var leg: one small-edit rung, single-digit
        # ms/event asserted — the O(touched region) scaling claim at
        # the size where a full sweep costs real time
        big_n = 100_000
        big = leg(_tree_factor_arrays(big_n, span=100, seed=7),
                  big_n, 1, 8, warmup, budget, seed=53)
        if big["ms_per_event"] >= 10.0:
            raise RuntimeError(
                f"roi contract violated: 1-row edits at {big_n} vars "
                f"ran {big['ms_per_event']} ms/event; ISSUE 16 "
                f"requires single-digit ms/event")
        value["ladder_100k"] = {"vars": big_n, "edit_1": big}

    return {
        "metric": f"roi_warm_ladder_{n}var",
        "value": value,
        "unit": "ms per warm event (median), ROI vs full sweep",
        "contracts_asserted": True,  # retrace-free + gate-engaged +
        # settled-region bit-exactness + (full) 5x and single-digit
        "hardware": jax.default_backend(),
    }


def _portfolio_preempt_leg(work, quick=False):
    """The ISSUE 17 preemption leg: a REAL kill -9 mid-RACE, then
    resume.  Three subprocess runs of the same ``solve --portfolio
    auto`` job (mirrors ``_chaos_preempt_leg``, but the snapshot is
    the survivor SET — group carries + referee state + per-arm best
    selections):

    1. uninterrupted (the oracle);
    2. checkpointed with ``PYDCOP_TPU_PREEMPT_AFTER=2`` — SIGKILL
       right after the second boundary snapshot lands, i.e. mid-race
       with kills possibly already decided;
    3. ``--resume`` — restores the survivor set and races on.

    Asserted: the kill happened (SIGKILL exit), the resume restored
    (``resumed_from_cycle`` > 0), and the resumed run reproduces the
    uninterrupted race's winner, assignment, cycle AND the full
    per-arm portfolio block bit-exactly — scoring and kill decisions
    are pure functions of the restored state."""
    import os
    import signal
    import subprocess
    import sys as _sys

    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.generators.graphcoloring import \
        generate_graph_coloring

    n = 49 if quick else 144
    max_cycles = 96 if quick else 160
    every = 16
    inst = os.path.join(work, "portfolio_preempt.yaml")
    with open(inst, "w") as f:
        f.write(dcop_yaml(generate_graph_coloring(
            n, 3, "grid", soft=True, seed=11)))
    ck_dir = os.path.join(work, "portfolio_ck")
    argv = [_sys.executable, "-m", "pydcop_tpu.dcop_cli", "solve",
            "-a", "maxsum", "--max_cycles", str(max_cycles),
            "--seed", "7", "--portfolio", "auto",
            "--portfolio-every", str(every)]
    ck_args = ["--checkpoint", ck_dir,
               "--checkpoint-every", str(every)]

    def run(extra, env_extra=None):
        env = dict(os.environ, **(env_extra or {}))
        return subprocess.run(argv + extra + [inst],
                              capture_output=True, text=True,
                              env=env, timeout=600)

    oracle = run([])
    if oracle.returncode != 0:
        raise RuntimeError(f"portfolio preempt leg oracle failed: "
                           f"{oracle.stderr[-400:]}")
    oracle_res = json.loads(oracle.stdout)

    killed = run(ck_args, {"PYDCOP_TPU_PREEMPT_AFTER": "2"})
    if killed.returncode != -signal.SIGKILL:
        raise RuntimeError(
            f"portfolio preempt leg: expected a SIGKILL mid-race, "
            f"got exit {killed.returncode}: {killed.stderr[-400:]}")

    resumed = run(ck_args + ["--resume"])
    if resumed.returncode != 0:
        raise RuntimeError(f"portfolio preempt leg resume failed: "
                           f"{resumed.stderr[-400:]}")
    res = json.loads(resumed.stdout)
    if not res.get("resumed_from_cycle"):
        raise RuntimeError(
            f"portfolio preempt leg: resume did not restore "
            f"(resumed_from_cycle="
            f"{res.get('resumed_from_cycle')!r})")
    for k in ("cycle", "assignment", "status", "portfolio"):
        if res[k] != oracle_res[k]:
            raise RuntimeError(
                f"portfolio preempt leg NOT bit-exact: {k} differs "
                f"after resume ({res[k]!r} vs {oracle_res[k]!r})")
    return {
        "vars": n, "max_cycles": max_cycles,
        "killed_exit": killed.returncode,
        "resumed_from_cycle": res["resumed_from_cycle"],
        "winner": res["portfolio"]["winner"],
        "arms_killed": res["portfolio"]["arms_killed"],
        "bit_exact": True,
    }


def bench_portfolio(quick=False):
    """Solver-portfolio arm races (ISSUE 17): the 8-arm ``auto`` grid
    vs each arm run solo, on a loopy 2-D grid coloring — the no-
    dominant-config workload the decimation/DSA benches measured.
    One instance rides every lane; arms differ by family, seed,
    damping, decimation schedule and DSA variant.

    Both legs run WARM through one :class:`ExecutableCache` (a first
    untimed pass pays the compiles, exactly the serve restart shape),
    so the walls compare racing work against solving work, not
    compile counts.

    Asserted, not eyeballed:

    * the winner's ``(violations, cost)`` is <= the best SOLO arm's —
      early kills must not cost answer quality (per-lane trajectories
      are bit-identical racing or solo, so the race can only lose by
      killing the eventual winner);
    * the race wall is <= 2x the MEDIAN solo arm's wall: racing 8
      configs costs about one config, not eight;
    * early kills reclaim >= 50% of the naive 8x lane-cycles
      (sum of per-arm cycles survived vs arms x budget);
    * retrace-free: every compiled program identity (family x
      hyperparams x pow2 lane count) is opened exactly once across
      the race — rebatches re-open smaller rungs, never re-open the
      same one;
    * a mid-race ``kill -9`` + ``--resume`` reproduces the
      uninterrupted race's winner, assignment and per-arm block
      bit-exactly (subprocess leg, real SIGKILL).

    Host-CPU numbers, honestly labeled."""
    import os
    import shutil
    import tempfile

    import jax
    import numpy as np

    from pydcop_tpu.engine._cache import ExecutableCache
    from pydcop_tpu.generators.graphcoloring import \
        generate_graph_coloring
    from pydcop_tpu.parallel.portfolio import (PortfolioRace,
                                               parse_portfolio_spec)

    n = 625 if quick else 10_000           # square: 2-D grid mesh
    budget = 256 if quick else 512
    every = 16
    # an ops-style referee: aggressive enough that losing arms die at
    # the FIRST boundary — both the reclaim and the <=2x wall
    # contracts depend on it (each extra all-arms boundary costs ~7
    # more group programs than the post-kill tail)
    knobs = dict(every=every, margin=0.05, patience=1, plateau=4)
    dcop = generate_graph_coloring(n, 3, "grid", soft=True, seed=9)
    arms = parse_portfolio_spec("auto", base_seed=0)
    work = tempfile.mkdtemp(prefix="pydcop_bench_portfolio_")
    try:
        cache = ExecutableCache(path=os.path.join(work, "exec"))
        ikey = ("bench_portfolio", n, 9)

        opens = []

        class _Race(PortfolioRace):
            def _open_group(self, group, lane_arms, init_keys=None):
                opens.append((group.algo,
                              tuple(sorted((k, str(v)) for k, v in
                                           group.params.items())),
                              len(lane_arms)))
                return super()._open_group(group, lane_arms,
                                           init_keys=init_keys)

        def race_once():
            return _Race(dcop, arms, max_cycles=budget,
                         exec_cache=cache, instance_key=ikey,
                         **knobs).run()

        def solo_once(arm):
            return PortfolioRace(dcop, [arm], max_cycles=budget,
                                 exec_cache=cache, instance_key=ikey,
                                 **knobs).run()

        # untimed warm pass: every program compiles once into the
        # executable cache (the serve-restart shape)
        race_once()
        for arm in arms:
            solo_once(arm)

        solo_walls, solo_scores = [], {}
        for arm in arms:
            t0 = time.perf_counter()
            r = solo_once(arm)
            solo_walls.append(time.perf_counter() - t0)
            solo_scores[arm.label] = (r["violation"], r["cost"])
        best_solo = min(solo_scores.values())
        median_solo = float(np.median(solo_walls))

        opens.clear()
        t0 = time.perf_counter()
        res = race_once()
        race_wall = time.perf_counter() - t0
        block = res["portfolio"]

        if (res["violation"], res["cost"]) > best_solo:
            raise RuntimeError(
                f"portfolio contract violated: race winner "
                f"{block['winner']} scored {res['violation']} viol / "
                f"{res['cost']}, worse than the best solo arm "
                f"{best_solo} — early kills cost answer quality")
        # quick mode's 625-var rung finishes in well under a second,
        # where host-scheduler jitter is a visible fraction of the
        # wall — the strict 2x bound is the full-mode contract
        # (mirrors bench_roi's full-only headline)
        wall_bound = 3.0 if quick else 2.0
        if race_wall > wall_bound * median_solo:
            raise RuntimeError(
                f"portfolio contract violated: the 8-arm race took "
                f"{race_wall:.2f}s, more than {wall_bound:g}x the "
                f"median solo arm's {median_solo:.2f}s — kills are "
                f"not reclaiming the lanes")
        naive = len(arms) * budget
        spent = sum(row["cycles"] for row in block["arms"])
        reclaimed = 1.0 - spent / naive
        if reclaimed < 0.5:
            raise RuntimeError(
                f"portfolio contract violated: early kills reclaimed "
                f"only {reclaimed:.0%} of the naive {len(arms)}x "
                f"lane-cycles (spent {spent} of {naive}); ISSUE 17 "
                f"requires >= 50%")
        if len(opens) != len(set(opens)):
            dupes = sorted({o for o in opens if opens.count(o) > 1})
            raise RuntimeError(
                f"portfolio retrace: program identities opened more "
                f"than once during the race: {dupes}")

        preempt = _portfolio_preempt_leg(work, quick=quick)

        return {
            "metric": f"portfolio_race_{n}var",
            "value": {
                "vars": n, "arms": len(arms), "budget": budget,
                "referee": dict(knobs),
                "winner": block["winner"],
                "winner_cost": round(res["cost"], 4),
                "best_solo_cost": round(best_solo[1], 4),
                "win_margin": (round(block["win_margin"], 4)
                               if block["win_margin"] is not None
                               else None),
                "race_wall_s": round(race_wall, 3),
                "solo_wall_s": {
                    "median": round(median_solo, 3),
                    "sum": round(float(np.sum(solo_walls)), 3)},
                "race_vs_median_solo": round(
                    race_wall / max(median_solo, 1e-9), 2),
                "arms_killed": block["arms_killed"],
                "rebatches": block["rebatches"],
                "reclaimed_lane_cycles_frac": round(reclaimed, 4),
                "programs_opened": len(opens),
                "preempt": preempt,
            },
            "unit": "8-arm race wall vs solo arms (warm, seconds)",
            "contracts_asserted": True,  # quality + <=2x wall +
            # >=50% reclaim + retrace-free + kill -9 resume bit-exact
            "hardware": jax.default_backend(),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def bench_serve_dynamic(quick=False, out_dir=None):
    """Sustained mixed delta+cold load through an in-process serve
    loop (ISSUE 12): N warm delta sessions under a byte budget sized
    to hold only PART of them, interleaved with cold solve jobs —
    the millions-of-users traffic shape where almost every request is
    a small edit against hot state.

    Measures p50/p99 latency per job kind (solve: queue wait +
    amortized execute; delta: apply + execute spans) and asserts:

    * the byte budget is respected — the session store's resident
      gauge is <= the budget after EVERY delta dispatch (read off the
      dispatch records' ``sessions`` snapshot);
    * evictions actually happened (the budget bites) and a delta
      against an evicted target reopened WARM through the executable
      cache — some reopening dispatch shows ``deserialize_s`` and no
      ``compile_s`` in its open spans;
    * warm (non-opening) delta dispatches carry zero
      ``compile_s``/``trace_lower_s`` spans;
    * (full mode) the resident scatter path beats the re-upload path
      on mean warm ms/event.

    ``out_dir`` keeps the per-leg serve JSONL files (the test tier
    runs ``pydcop telemetry-validate`` over them); default is a
    temp dir.  Host-CPU numbers, labeled."""
    import os
    import shutil
    import tempfile

    import jax
    import numpy as np

    from pydcop_tpu.dcop.yamldcop import (dcop_yaml,
                                          load_dcop_from_file)
    from pydcop_tpu.dynamics import DynamicEngine
    from pydcop_tpu.engine._cache import ExecutableCache
    from pydcop_tpu.generators.graphcoloring import \
        generate_graph_coloring
    from pydcop_tpu.observability.report import (RunReporter,
                                                 read_records)
    from pydcop_tpu.serving.daemon import ServeLoop
    from pydcop_tpu.serving.dispatcher import Dispatcher
    from pydcop_tpu.serving.queue import AdmissionQueue

    n_targets = 3 if quick else 6
    n_rounds = 8 if quick else 20
    nv = 14 if quick else 256
    max_cycles = 40
    reserve = "2:8"
    keep = out_dir is not None
    work = out_dir or tempfile.mkdtemp(prefix="pydcop_sdyn_")
    os.makedirs(work, exist_ok=True)
    try:
        paths, factor_names, var_names = [], [], []
        for t in range(n_targets):
            dcop = generate_graph_coloring(
                nv, 3, "scalefree", m_edge=2, soft=True, seed=100 + t)
            p = os.path.join(work, f"target{t}.yaml")
            with open(p, "w") as f:
                f.write(dcop_yaml(dcop))
            paths.append(p)
            loaded = load_dcop_from_file(p)
            factor_names.append(sorted(loaded.constraints))
            var_names.append(sorted(loaded.variables))

        # size the byte budget off ONE real session: enough for about
        # half the targets, so the LRU policy must evict mid-stream
        probe = DynamicEngine(load_dcop_from_file(paths[0]),
                              reserve=reserve,
                              max_cycles=max_cycles)
        probe.solve()
        per_session = probe.resident_bytes()
        probe.close()
        budget = int(per_session * (n_targets / 2.0 + 0.25))

        # the stream is BURSTY per target (a session gets several
        # edits before traffic moves on — the realistic shape, and
        # the one an LRU can exploit): each round picks the next
        # target, sends `burst` deltas against it, then one cold
        # solve job.  With the budget below n_targets sessions, the
        # rotation forces evictions while the burst tail stays warm
        burst = 4
        rng = np.random.RandomState(5)
        lines = []
        for t in range(n_targets):
            lines.append(json.dumps({
                "id": f"j{t}", "dcop": paths[t], "algo": "maxsum",
                "max_cycles": max_cycles, "seed": t}))
        for r in range(n_rounds):
            t = r % n_targets
            for b in range(burst):
                if b == burst - 1 and r % 5 == 4:
                    u = int(rng.randint(0, nv))
                    v = (u + 1 + int(rng.randint(0, nv - 1))) % nv
                    actions = [
                        {"type": "add_constraint",
                         "name": f"dyn{r}_{b}",
                         "scope": [var_names[t][u],
                                   var_names[t][v]],
                         "costs": rng.randint(
                             0, 9, size=(3, 3)).tolist()},
                        {"type": "remove_constraint",
                         "name": f"dyn{r}_{b}"},
                    ]
                else:
                    picks = rng.choice(len(factor_names[t]), size=2,
                                       replace=False)
                    actions = [
                        {"type": "change_costs",
                         "name": factor_names[t][int(k)],
                         "costs": rng.randint(
                             0, 9, size=(3, 3)).tolist()}
                        for k in picks]
                lines.append(json.dumps({
                    "id": f"d{r}_{b}", "op": "delta",
                    "target": f"j{t}", "actions": actions}))
            lines.append(json.dumps({
                "id": f"cold{r}", "dcop": paths[t],
                "algo": "maxsum", "max_cycles": max_cycles,
                "seed": 1000 + r}))

        def pct(xs, p):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(len(xs) * p))]

        def leg(tag, resident, layout="edge_major"):
            out = os.path.join(work, f"serve_dynamic_{tag}.jsonl")
            if os.path.exists(out):
                os.remove(out)
            cache = ExecutableCache(
                path=os.path.join(work, f"exec_{tag}"))
            reporter = RunReporter(out, algo="serve", mode="serve")
            try:
                reporter.header(session_budget_bytes=budget,
                                reserve=reserve, leg=tag,
                                session_layout=layout)
                dispatcher = Dispatcher(
                    reporter=reporter, exec_cache=cache,
                    reserve=reserve, session_budget_bytes=budget,
                    resident_deltas=resident,
                    session_layout=layout)
                loop = ServeLoop(
                    AdmissionQueue(max_batch=4, max_delay_s=0.005),
                    dispatcher, reporter=reporter,
                    default_max_cycles=max_cycles, reserve=reserve)
                t0 = time.perf_counter()
                stats = loop.run_oneshot(lines)
                wall = time.perf_counter() - t0
            finally:
                reporter.close()
            if stats["rejected"]:
                raise RuntimeError(
                    f"{tag} leg rejected {stats['rejected']} jobs")
            records = read_records(out)
            deltas = [r for r in records
                      if r.get("record") == "serve"
                      and r.get("reason") == "delta"]
            n_deltas = n_rounds * burst
            if len(deltas) != n_deltas:
                raise RuntimeError(
                    f"{tag} leg dispatched {len(deltas)}/{n_deltas} "
                    f"deltas")
            # THE budget contract: resident gauge <= budget after
            # every single dispatch
            for rec in deltas:
                s = rec["sessions"]
                if s["resident_bytes"] > s["budget_bytes"]:
                    raise RuntimeError(
                        f"{tag} leg busted the session budget: "
                        f"{s['resident_bytes']} > "
                        f"{s['budget_bytes']} after a dispatch")
            warm = [r for r in deltas if not r["session_opened"]]
            for rec in warm:
                if "compile_s" in rec["spans"] or \
                        "trace_lower_s" in rec["spans"]:
                    raise RuntimeError(
                        f"{tag} leg warm delta traced/compiled: "
                        f"{rec['spans']}")
            # ISSUE 14: every delta dispatch echoes the RESOLVED
            # session layout plus the budget telemetry
            for rec in deltas:
                if rec.get("layout") != layout:
                    raise RuntimeError(
                        f"{tag} leg dispatched at layout "
                        f"{rec.get('layout')!r}, configured "
                        f"{layout!r}")
                if not isinstance(rec.get("cycles_run"), int):
                    raise RuntimeError(
                        f"{tag} leg dispatch missing cycles_run")
            # a REOPEN is an opening dispatch for a target that had
            # already opened earlier in the stream (i.e. it was
            # evicted in between) — initial opens of later targets
            # must not be misclassified, or the eviction-reopen
            # contract below passes vacuously
            seen_targets = set()
            reopens = []
            for r in deltas:
                if r["session_opened"]:
                    if r["target"] in seen_targets:
                        reopens.append(r)
                    seen_targets.add(r["target"])
            final = records[-1]
            evictions = final["sessions"]["evictions"]
            if evictions < 1:
                raise RuntimeError(
                    f"{tag} leg: budget never evicted "
                    f"(budget {budget}, sessions {final['sessions']})")
            if cache.enabled:
                # an evicted target's reopen must come back through
                # the executable cache: deserialize, no compile
                warm_reopens = [
                    r for r in reopens
                    if r.get("open_spans")
                    and "deserialize_s" in r["open_spans"]
                    and "compile_s" not in r["open_spans"]]
                if reopens and not warm_reopens:
                    raise RuntimeError(
                        f"{tag} leg: {len(reopens)} session reopens, "
                        f"none deserialized from the executable "
                        f"cache")
            # per-event service time, the schema's documented
            # convention: execute + apply wall MINUS the one-off
            # apply-scatter trace/compile (reported separately, like
            # compile_s never lands in a solve job's `time`)
            delta_ms = [1000 * (r["spans"].get("execute_s", 0.0)
                                + r["spans"].get("apply_s", 0.0)
                                - r["spans"].get(
                                    "apply_trace_lower_s", 0.0)
                                - r["spans"].get(
                                    "apply_compile_s", 0.0))
                        for r in warm]
            apply_compile_s = sum(
                r["spans"].get("apply_trace_lower_s", 0.0)
                + r["spans"].get("apply_compile_s", 0.0)
                for r in deltas)
            solves = [r for r in records
                      if r.get("record") == "summary"
                      and r.get("dispatch_reason") != "delta"
                      and r.get("status") != "REJECTED"]
            solve_ms = [1000 * (r["queue_wait_s"] + r["time"])
                        for r in solves]
            uploads = [r["upload_bytes"] for r in warm]
            return {
                "out": out,
                "delta_p50_ms": round(pct(delta_ms, 0.5), 2),
                "delta_p99_ms": round(pct(delta_ms, 0.99), 2),
                "delta_mean_ms": round(float(np.mean(delta_ms)), 2),
                "solve_p50_ms": round(pct(solve_ms, 0.5), 2),
                "solve_p99_ms": round(pct(solve_ms, 0.99), 2),
                "upload_bytes_per_event": int(np.mean(uploads)),
                "evictions": evictions,
                "evicted_bytes": final["sessions"]["evicted_bytes"],
                "session_reopens": len(reopens),
                "apply_compile_s": round(apply_compile_s, 3),
                "wall_s": round(wall, 3),
            }

        res = leg("resident", True)
        reup = leg("reupload", False)
        # ISSUE 14: the same mixed stream with sessions opened at the
        # lane layout (the stream carries constraint add/remove, so
        # fused is out by contract) — layout echo + budget telemetry
        # asserted inside the leg, latency reported alongside
        lane = leg("lane", True, layout="lane_major")
        if not quick and res["delta_p50_ms"] > reup["delta_p50_ms"]:
            raise RuntimeError(
                f"serve-dynamic contract violated: resident warm "
                f"deltas p50 {res['delta_p50_ms']} ms/event vs "
                f"re-upload {reup['delta_p50_ms']} ms/event")
        up_ratio = reup["upload_bytes_per_event"] / max(
            res["upload_bytes_per_event"], 1)
        if up_ratio < 10:
            raise RuntimeError(
                f"serve-dynamic contract violated: upload_bytes "
                f"only {up_ratio:.1f}x below re-upload")
        return {
            "metric": (f"serve_dynamic_{n_targets}targets_"
                       f"{n_rounds * burst}deltas"),
            "value": {"resident": res, "reupload": reup,
                      "lane_layout": lane,
                      "upload_reduction": round(up_ratio, 1),
                      "session_budget_bytes": budget},
            "unit": "ms latency percentiles per job kind",
            "contracts_asserted": True,
            "hardware": jax.default_backend(),
        }
    finally:
        if not keep:
            shutil.rmtree(work, ignore_errors=True)


def _chaos_preempt_leg(work, quick=False):
    """The ISSUE 15 preemption leg: a REAL kill -9 mid-solve, then
    resume.  Three subprocess runs of the same `solve` job:

    1. uninterrupted (the oracle);
    2. checkpointed with ``PYDCOP_TPU_PREEMPT_AFTER=2`` — the process
       SIGKILLs itself right after its second snapshot lands, i.e.
       genuinely dies mid-solve at a deterministic chunk boundary
       (no flaky timing-based kills);
    3. ``--resume`` — restores the snapshot and finishes.

    Asserted: the kill actually happened (SIGKILL exit), the resume
    actually restored (``resumed_from_cycle`` > 0), and the resumed
    run reproduces the uninterrupted run's selections AND cycle count
    bit-exactly."""
    import os
    import signal
    import subprocess
    import sys as _sys

    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.generators.graphcoloring import \
        generate_graph_coloring

    n = 48 if quick else 300
    max_cycles = 96 if quick else 256
    every = 16 if quick else 32
    inst = os.path.join(work, "preempt.yaml")
    with open(inst, "w") as f:
        f.write(dcop_yaml(generate_graph_coloring(
            n, 3, "scalefree", m_edge=2, soft=True, seed=11)))
    ck_dir = os.path.join(work, "preempt_ck")
    argv = [_sys.executable, "-m", "pydcop_tpu.dcop_cli", "solve",
            "-a", "maxsum", "--max_cycles", str(max_cycles),
            "--seed", "7", inst]
    ck_args = ["--checkpoint", ck_dir,
               "--checkpoint-every", str(every)]

    def run(extra, env_extra=None):
        env = dict(os.environ, **(env_extra or {}))
        return subprocess.run(argv[:-1] + extra + [inst],
                              capture_output=True, text=True,
                              env=env, timeout=600)

    oracle = run([])
    if oracle.returncode != 0:
        raise RuntimeError(
            f"preempt leg oracle failed: {oracle.stderr[-400:]}")
    oracle_res = json.loads(oracle.stdout)

    killed = run(ck_args, {"PYDCOP_TPU_PREEMPT_AFTER": "2"})
    if killed.returncode != -signal.SIGKILL:
        raise RuntimeError(
            f"preempt leg: expected a SIGKILL mid-solve, got exit "
            f"{killed.returncode}: {killed.stderr[-400:]}")

    resumed = run(ck_args + ["--resume"])
    if resumed.returncode != 0:
        raise RuntimeError(
            f"preempt leg resume failed: {resumed.stderr[-400:]}")
    res = json.loads(resumed.stdout)
    if not res.get("resumed_from_cycle"):
        raise RuntimeError(
            f"preempt leg: resume did not restore a snapshot "
            f"(resumed_from_cycle={res.get('resumed_from_cycle')!r})")
    if res["cycle"] != oracle_res["cycle"] \
            or res["assignment"] != oracle_res["assignment"]:
        raise RuntimeError(
            f"preempt leg NOT bit-exact: resumed cycle "
            f"{res['cycle']} vs {oracle_res['cycle']}, assignments "
            f"{'equal' if res['assignment'] == oracle_res['assignment'] else 'DIFFER'}")
    return {
        "vars": n, "max_cycles": max_cycles,
        "killed_exit": killed.returncode,
        "resumed_from_cycle": res["resumed_from_cycle"],
        "checkpoint_bytes": res.get("checkpoint_bytes"),
        "cycle": res["cycle"],
        "bit_exact": True,
    }


def _chaos_roi_leg(quick=False):
    """The ISSUE 16 warm-session leg: an ROI delta session follows
    serve's crash-recovery contract — snapshot the post-base-solve
    carry (the ISSUE 15 checkpoint/journal division of labor, now
    including the activity plane + frontier state), restore it into a
    fresh engine, replay the FULL delta tail — and must land
    bit-exactly where the never-crashed session did: selections,
    cycles, active fractions and frontier counts all equal, cost to
    float tolerance.  A restore into a full-sweep engine must be
    REFUSED loudly (the roi flag rides the snapshot fingerprint)."""
    import numpy as np

    from pydcop_tpu.dynamics import DynamicEngine
    from pydcop_tpu.robustness.checkpoint import CheckpointError

    n = 400 if quick else 2000
    tree = _tree_factor_arrays(n, span=50, seed=5)
    rng = np.random.RandomState(9)
    tail = [
        [{"type": "change_costs", "name": f"c{int(f)}",
          "costs": rng.randint(0, 9, size=(3, 3)).tolist()}
         for f in rng.randint(0, n - 1, size=2)]
        for _ in range(4)]

    def mk(roi=True):
        return DynamicEngine(tree, reserve="2:16", max_cycles=800,
                             layout="fused", warm_budget="adaptive",
                             roi=roi)

    live = mk()
    if live.solve()["status"] != "FINISHED":
        raise RuntimeError("roi chaos leg: base solve did not "
                           "converge; pick a settling instance")
    snap = live.state_snapshot()
    want = []
    for ev in tail:
        live.apply(ev)
        r = live.solve()
        want.append((r["assignment"], r["cycle"],
                     r["active_fraction"], r["frontier_expansions"],
                     r["cost"]))

    # the refusal gate first: the snapshot must NOT restore into a
    # differently-configured (full-sweep) engine
    refused = False
    try:
        mk(roi=False).restore_state(snap)
    except CheckpointError as e:
        refused = "roi" in str(e)
    if not refused:
        raise RuntimeError(
            "roi chaos leg: a full-sweep engine accepted an ROI "
            "session snapshot (or refused without naming roi)")

    twin = mk()
    twin.restore_state(snap)
    for i, (ev, (asg, cyc, af, fx, cost)) in enumerate(
            zip(tail, want)):
        twin.apply(ev)
        r = twin.solve()
        if (r["assignment"], r["cycle"], r["active_fraction"],
                r["frontier_expansions"]) != (asg, cyc, af, fx) \
                or not np.isclose(r["cost"], cost):
            raise RuntimeError(
                f"roi chaos leg NOT bit-exact at tail event {i}: "
                f"restored session (cycle {r['cycle']}, af "
                f"{r['active_fraction']}, fx "
                f"{r['frontier_expansions']}, cost {r['cost']}) vs "
                f"live (cycle {cyc}, af {af}, fx {fx}, cost {cost})")
    live.close()
    twin.close()
    return {"vars": n, "tail_events": len(tail),
            "active_fraction": [w[2] for w in want],
            "refused_full_sweep_restore": True,
            "bit_exact": True}


def bench_chaos(quick=False, out_dir=None):
    """The chaos contract (ISSUE 13): the `bench_serve`-shaped mixed
    load — cold maxsum + dsa solves plus warm delta traffic — driven
    through an in-process serve loop TWICE: fault-free (the control)
    and under a seeded 5% fault plan (execute_error poisoning drawn
    per job id, a scheduled transient dispatch failure the backoff
    retry absorbs, scheduled nan_planes admissions, and rate-drawn
    cache_corrupt on the executable cache).  Asserted, not eyeballed:

    * the daemon never crashes — both legs drain to the final serve
      record;
    * every healthy job completes: the non-rejected summary set is
      exactly (all jobs - expected rejected set);
    * ONLY the plan's poisoned jobs are rejected, each with the
      structured ``poisoned`` (execute_error via retry+bisection, or
      direct for deltas) / ``nan_planes`` (admission finite gate)
      reason class — and nothing is shed ``circuit_open`` (bisection
      isolating poisoned INPUTS must never quarantine a healthy
      rung);
    * retries and bisections actually happened (the machinery under
      test ran);
    * degradation bound: the chaos leg's solve p99 latency
      (queue_wait + amortized execute) stays within 2x the
      fault-free leg's (plus a 0.25 s absolute floor so a ~50 ms
      control p99 on a noisy CI host cannot fail the 2x bound on
      scheduler jitter alone).

    ``out_dir`` keeps the per-leg serve JSONL (the tier-1 quick leg
    telemetry-validates them).  Host-CPU numbers, labeled."""
    import os
    import shutil
    import tempfile

    import jax
    import numpy as np

    from pydcop_tpu.dcop.yamldcop import (dcop_yaml,
                                          load_dcop_from_file)
    from pydcop_tpu.engine._cache import ExecutableCache
    from pydcop_tpu.generators.graphcoloring import \
        generate_graph_coloring
    from pydcop_tpu.observability.report import (RunReporter,
                                                 read_records)
    from pydcop_tpu.serving.daemon import ServeLoop
    from pydcop_tpu.serving.dispatcher import Dispatcher
    from pydcop_tpu.serving.faults import FaultPlan
    from pydcop_tpu.serving.queue import AdmissionQueue

    # quick: one size band (one rung per algo family) bounds the
    # compile universe for the tier-1 leg; full: the bench_serve two-
    # band shape at >= 400 mixed jobs, the acceptance-criteria scale
    n_jobs = 120 if quick else 432
    sizes = (12, 14, 16) if quick else (12, 14, 16, 20, 24, 28)
    n_targets = 3
    max_cycles = 10
    keep = out_dir is not None
    work = out_dir or tempfile.mkdtemp(prefix="pydcop_chaos_")
    os.makedirs(work, exist_ok=True)
    try:
        paths, factor_names = [], []
        for nv in sizes:
            dcop = generate_graph_coloring(
                nv, 3, "scalefree", m_edge=2, soft=True, seed=nv)
            p = os.path.join(work, f"i{nv}.yaml")
            with open(p, "w") as f:
                f.write(dcop_yaml(dcop))
            paths.append(p)
            factor_names.append(
                sorted(load_dcop_from_file(p).constraints))

        # the mixed stream: targets first (maxsum solves deltas can
        # land on), then alternating maxsum/dsa solves with a delta
        # every 6th job — cold + warm traffic interleaved
        rng = np.random.RandomState(13)
        lines, all_ids, delta_ids, solve_ids = [], [], [], []
        for t in range(n_targets):
            jid = f"j{t}"
            lines.append(json.dumps({
                "id": jid, "dcop": paths[t], "algo": "maxsum",
                "max_cycles": max_cycles, "seed": t}))
            all_ids.append(jid)
            solve_ids.append(jid)
        i = n_targets
        while len(all_ids) < n_jobs:
            if i % 6 == 5:
                t = i % n_targets
                jid = f"d{i}"
                picks = rng.choice(len(factor_names[t]), size=2,
                                   replace=False)
                lines.append(json.dumps({
                    "id": jid, "op": "delta", "target": f"j{t}",
                    "actions": [
                        {"type": "change_costs",
                         "name": factor_names[t][int(k)],
                         "costs": rng.randint(
                             0, 9, size=(3, 3)).tolist()}
                        for k in picks]}))
                delta_ids.append(jid)
            else:
                jid = f"s{i}"
                lines.append(json.dumps({
                    "id": jid, "dcop": paths[i % len(paths)],
                    "algo": "maxsum" if i % 2 else "dsa",
                    "max_cycles": max_cycles, "seed": i}))
                solve_ids.append(jid)
            all_ids.append(jid)
            i += 1

        # the 5% plan: execute_error poisoning by job id (sticky, so
        # bisection isolates it), one transient dispatch-index fault
        # (the retry absorbs it), two scheduled nan_planes
        # admissions, and cache_corrupt drawn per cache file
        rate_only = FaultPlan(seed=0, rate=0.05,
                              points=("execute_error",))
        nan_ids = [j for j in solve_ids[n_targets:]
                   if not rate_only.job_fires("execute_error", j)][:2]
        plan = FaultPlan(
            seed=0, rate=0.05,
            points=("execute_error", "cache_corrupt"),
            schedule=(
                [{"point": "execute_error", "dispatch_index": 2}]
                + [{"point": "nan_planes", "job_id": j}
                   for j in nan_ids]))
        poisoned = set(plan.poisoned_jobs("execute_error", all_ids))
        expected_rejected = poisoned | set(nan_ids)
        if not poisoned or not (set(delta_ids) & poisoned):
            raise RuntimeError(
                "chaos plan drew no poisoned solve/delta jobs; "
                "change the seed so the bench exercises bisection "
                "AND delta poisoning")

        def pct(xs, p):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(len(xs) * p))]

        def leg(tag, faults):
            out = os.path.join(work, f"chaos_{tag}.jsonl")
            if os.path.exists(out):
                os.remove(out)
            cache = ExecutableCache(
                path=os.path.join(work, "exec_shared"))
            if faults is not None:
                cache.faults = faults
            reporter = RunReporter(out, algo="serve", mode="serve")
            try:
                reporter.header(leg=tag, fault_plan=bool(faults),
                                n_jobs=n_jobs)
                dispatcher = Dispatcher(
                    reporter=reporter, exec_cache=cache,
                    faults=faults)
                loop = ServeLoop(
                    AdmissionQueue(max_batch=8, max_delay_s=0.02),
                    dispatcher, reporter=reporter,
                    default_max_cycles=max_cycles,
                    faults=faults, retry_backoff_s=0.01)
                t0 = time.perf_counter()
                stats = loop.run_oneshot(lines)
                wall = time.perf_counter() - t0
            finally:
                reporter.close()
            records = read_records(out)
            if records[-1].get("event") not in ("drained",):
                raise RuntimeError(
                    f"{tag} leg did not drain: {records[-1]}")
            summaries = [r for r in records
                         if r.get("record") == "summary"]
            done = {r["job_id"] for r in summaries
                    if r.get("status") != "REJECTED"}
            rejected = {r["job_id"]: r for r in summaries
                        if r.get("status") == "REJECTED"}
            solve_lat = [r["queue_wait_s"] + r["time"]
                         for r in summaries
                         if r.get("status") != "REJECTED"
                         and "queue_wait_s" in r]
            return {
                "stats": stats, "records": records, "done": done,
                "rejected": rejected, "wall_s": round(wall, 3),
                "out": out,
                "p99_s": round(pct(solve_lat, 0.99), 4),
                "p50_s": round(pct(solve_lat, 0.5), 4),
                "cache_corrupt": cache.stats.get("corrupt", 0),
            }

        # warm the shared executable cache first — WITH the fault
        # plan, so the bisection-created batch shapes (4/2/1) land in
        # the cache too: both measured legs then run steady-state
        # (deserialize, not compile), which is the regime the 2x
        # degradation bound is about.  A cold-control comparison
        # would pass vacuously (control pays every compile); a
        # cold-chaos one would fail on one-off compile costs a real
        # restarted daemon never re-pays
        leg("warmup", plan)
        control = leg("control", None)
        if control["rejected"] or control["done"] != set(all_ids):
            raise RuntimeError(
                f"control leg must complete everything: "
                f"{len(control['done'])}/{n_jobs} done, "
                f"{sorted(control['rejected'])} rejected")
        chaos = leg("chaos", plan)

        # ---- the chaos contract ----
        if chaos["done"] != set(all_ids) - expected_rejected:
            missing = (set(all_ids) - expected_rejected) \
                - chaos["done"]
            extra = chaos["done"] & expected_rejected
            raise RuntimeError(
                f"chaos leg: healthy jobs missing {sorted(missing)}, "
                f"poisoned jobs completed {sorted(extra)}")
        if set(chaos["rejected"]) != expected_rejected:
            raise RuntimeError(
                f"chaos leg rejected {sorted(chaos['rejected'])}, "
                f"expected {sorted(expected_rejected)}")
        for jid, rec in chaos["rejected"].items():
            want = "nan_planes" if jid in nan_ids else "poisoned"
            if rec.get("reason_class") != want:
                raise RuntimeError(
                    f"chaos leg: {jid} rejected as "
                    f"{rec.get('reason_class')!r}, want {want!r}")
        if any(r.get("reason_class") == "circuit_open"
               for r in chaos["rejected"].values()):
            raise RuntimeError(
                "chaos leg shed healthy jobs circuit_open; the "
                "breaker must not trip on poisoned inputs")
        if chaos["stats"]["retries"] < 1 \
                or chaos["stats"]["bisections"] < 1:
            raise RuntimeError(
                f"chaos leg exercised no retry/bisection: "
                f"{chaos['stats']}")
        bound = max(2.0 * control["p99_s"],
                    control["p99_s"] + 0.25)
        if chaos["p99_s"] > bound:
            raise RuntimeError(
                f"chaos p99 {chaos['p99_s']}s exceeds the "
                f"degradation bound {bound:.4f}s (control p99 "
                f"{control['p99_s']}s)")
        # ---- the preemption leg (ISSUE 15): kill -9 mid-solve at a
        # deterministic checkpoint, --resume, assert bit-exactness
        preempt = _chaos_preempt_leg(work, quick=quick)
        # ---- the ROI warm-session leg (ISSUE 16): snapshot ->
        # restore -> replay-tail bit-exactness, roi-flag refusal
        roi_leg = _chaos_roi_leg(quick=quick)
        return {
            "metric": f"serve_chaos_{n_jobs}job_5pct_faults",
            "value": {
                "control": {"p50_s": control["p50_s"],
                            "p99_s": control["p99_s"],
                            "wall_s": control["wall_s"],
                            "out": control["out"]},
                "chaos": {"p50_s": chaos["p50_s"],
                          "p99_s": chaos["p99_s"],
                          "wall_s": chaos["wall_s"],
                          "out": chaos["out"],
                          "retries": chaos["stats"]["retries"],
                          "bisections": chaos["stats"]["bisections"],
                          "poisoned": chaos["stats"]["poisoned"],
                          "cache_corrupt": chaos["cache_corrupt"]},
                "poisoned_jobs": sorted(poisoned),
                "nan_jobs": sorted(nan_ids),
                "p99_degradation": round(
                    chaos["p99_s"] / max(control["p99_s"], 1e-9), 2),
                "preempt": preempt,
                "roi_session": roi_leg,
            },
            "unit": "latency percentiles under a 5% fault plan",
            "contracts_asserted": True,
            "hardware": jax.default_backend(),
        }
    finally:
        if not keep:
            shutil.rmtree(work, ignore_errors=True)


def bench_fleet(quick=False, out_dir=None):
    """The serve-fleet contract (ISSUE 19): N real worker daemons
    (subprocesses) behind one consistent-hash router, driven with the
    mixed cold+delta stream.  Asserted, not eyeballed:

    * throughput scale-out at 1/2(/4 full) workers over a SHARED
      pre-warmed executable cache (every leg runs deserialize-steady-
      state, so the jobs/s ratio measures dispatch concurrency, not
      compile amortization).  The near-linear asserts (>= 1.7x at 2
      workers, >= 3x at 4) are gated on the host actually having
      that many cores — on a smaller host the legs still run and the
      bench asserts no-collapse (>= 0.35x single-worker) and records
      ``scaling_asserted: false`` with the reason;
    * rolling restart mid-stream loses ZERO jobs — queued jobs come
      back through the drained worker's requeue-<id>.jsonl (router
      merge), in-flight jobs re-send from the router's pending
      table, and the restarted leg's dispatch spans show
      ``deserialize_s`` and ZERO ``compile_s`` (warm sessions came
      back by journal recovery through the shared cache, nothing
      recompiled);
    * ``kill -9`` of one worker mid-load: every healthy job
      completes, and the dead worker's warm session migrates — its
      post-failover delta selections/costs/cycles are BIT-EXACT
      against the uninterrupted single-worker oracle leg (the
      journal replays the exact pre-kill sequence).  The kill lands
      while cold solves are in flight (trivially re-sendable);
      resent deltas are at-least-once, so fleet delta traffic under
      failover should be idempotent edits (change_costs), which is
      what this stream uses;
    * the aggregated ``stats`` fan-out answers with every live
      worker's snapshot riding along (what repeatable serve-status
      renders).

    ``--max-batch 1`` everywhere: a deterministic one-rung-per-
    (algo, size) compile universe that the warmup leg fully
    pre-warms, keeping the zero-compile contract assertable.
    ``out_dir`` keeps the per-leg shared JSONL telemetry (the tier-1
    quick leg telemetry-validates it).  Host-CPU numbers, labeled."""
    import os
    import shutil
    import signal as _signal
    import tempfile

    import jax

    from pydcop_tpu.dcop.yamldcop import (dcop_yaml,
                                          load_dcop_from_file)
    from pydcop_tpu.generators.graphcoloring import \
        generate_graph_coloring
    from pydcop_tpu.observability.report import (RunReporter,
                                                 read_records)
    from pydcop_tpu.serving.fleet import (FleetManager, FleetRouter,
                                          ROUTER_ID)

    sizes = (10,) if quick else (12, 14, 16)
    n_targets = 2 if quick else 3
    n_jobs = 18 if quick else 96
    max_cycles = 6 if quick else 10
    worker_counts = (1, 2) if quick else (1, 2, 4)
    keep = out_dir is not None
    work = out_dir or tempfile.mkdtemp(prefix="pydcop_fleet_")
    os.makedirs(work, exist_ok=True)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env = {"PYTHONPATH": repo_root + (
        ":" + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else "")}
    # ONE executable cache for every leg (appended last, so it
    # overrides the per-leg FleetManager default): the warmup is paid
    # once, all measured legs deserialize
    shared_exec = os.path.join(work, "exec_shared")
    try:
        paths, factor_names = [], []
        for nv in sizes:
            dcop = generate_graph_coloring(
                nv, 3, "scalefree", m_edge=2, soft=True, seed=nv)
            p = os.path.join(work, f"i{nv}.yaml")
            with open(p, "w") as f:
                f.write(dcop_yaml(dcop))
            paths.append(p)
            factor_names.append(
                sorted(load_dcop_from_file(p).constraints))

        # the stream: maxsum targets first, then alternating cold
        # solves with an idempotent change_costs delta every 3rd job
        lines, delta_ids, all_ids = [], [], []
        for t in range(n_targets):
            jid = f"j{t}"
            lines.append(json.dumps({
                "id": jid, "dcop": paths[t % len(paths)],
                "algo": "maxsum", "max_cycles": max_cycles,
                "seed": t}))
            all_ids.append(jid)
        i = n_targets
        while len(all_ids) < n_jobs:
            if i % 3 == 2:
                t = (i // 3) % n_targets
                jid = f"d{i}"
                fn = factor_names[t % len(factor_names)]
                lines.append(json.dumps({
                    "id": jid, "op": "delta", "target": f"j{t}",
                    "actions": [{
                        "type": "change_costs",
                        "name": fn[i % len(fn)],
                        "costs": [[(i + r + c) % 9 for c in range(3)]
                                  for r in range(3)]}]}))
                delta_ids.append(jid)
            else:
                jid = f"s{i}"
                lines.append(json.dumps({
                    "id": jid, "dcop": paths[i % len(paths)],
                    "algo": "maxsum" if i % 2 else "dsa",
                    "max_cycles": max_cycles, "seed": i}))
            all_ids.append(jid)
            i += 1
        solve_lines = [ln for ln in lines
                       if json.loads(ln).get("op", "solve")
                       == "solve"
                       and json.loads(ln)["id"].startswith("s")]

        def start_fleet(tag, n_workers):
            mgr = FleetManager(
                os.path.join(work, f"fleet_{tag}"), env=env,
                max_batch=1, max_delay_ms=5.0,
                max_cycles=max_cycles,
                worker_args=["--exec-cache", shared_exec])
            reporter = RunReporter(mgr.out, algo="serve",
                                   mode="serve", worker_id=ROUTER_ID)
            reporter.header(leg=tag, fleet_workers=n_workers)
            router = FleetRouter(reporter=reporter,
                                 checkpoint_dir=mgr.ckpt_dir)
            mgr.start(router, n_workers)
            return mgr, router, reporter

        def run_leg(tag, n_workers):
            mgr, router, reporter = start_fleet(tag, n_workers)
            replies = {}
            try:
                t0 = time.perf_counter()
                for ln in lines:
                    router.feed(
                        ln, reply=lambda r: replies.__setitem__(
                            r.get("job_id") or r.get("id"), r))
                if not router.drain(timeout=900):
                    raise RuntimeError(
                        f"{tag}: fleet did not drain "
                        f"({len(replies)}/{n_jobs} replied)")
                wall = time.perf_counter() - t0
            finally:
                mgr.shutdown(router)
                reporter.close()
            rejected = sorted(j for j, r in replies.items()
                              if r.get("status") == "REJECTED")
            if rejected or set(replies) != set(all_ids):
                raise RuntimeError(
                    f"{tag}: incomplete/rejected: "
                    f"{sorted(set(all_ids) - set(replies))} missing, "
                    f"{rejected} rejected")
            return {"wall_s": round(wall, 3),
                    "jobs_s": round(n_jobs / wall, 2),
                    "replies": replies, "out": mgr.out}

        # ---- warmup (compiles the whole rung universe into the
        # shared cache) then the measured throughput ladder; the
        # single-worker leg doubles as the bit-exactness oracle
        run_leg("warmup", 1)
        legs = {n: run_leg(f"throughput_{n}w", n)
                for n in worker_counts}
        oracle = legs[1]["replies"]
        cores = os.cpu_count() or 1
        base = legs[1]["jobs_s"]
        scaling = {}
        for n in worker_counts[1:]:
            want = {2: 1.7, 4: 3.0}[n]
            ratio = round(legs[n]["jobs_s"] / base, 2)
            asserted = cores >= n
            if asserted and ratio < want:
                raise RuntimeError(
                    f"fleet scaling: {n} workers gave {ratio}x "
                    f"(want >= {want}x) on a {cores}-core host")
            if not asserted and ratio < 0.35:
                raise RuntimeError(
                    f"fleet collapsed at {n} workers: {ratio}x "
                    f"single-worker throughput")
            scaling[n] = {
                "jobs_s": legs[n]["jobs_s"], "ratio_vs_1w": ratio,
                "scaling_asserted": asserted,
                **({} if asserted else {
                    "reason": f"host has {cores} core(s), "
                              f"needs >= {n}"})}

        # ---- rolling restart mid-stream: zero lost jobs, zero
        # compiles (requeue merge + pending re-send + journal
        # recovery through the warm shared cache)
        mgr, router, reporter = start_fleet("restart", 2)
        replies = {}

        def _reply(r):
            replies[r.get("job_id") or r.get("id")] = r

        try:
            cut = int(len(lines) * 0.6)
            for ln in lines[:cut]:
                router.feed(ln, reply=_reply)
            # restart w0 with the stream mid-flight: its queued jobs
            # requeue, its in-flight jobs re-send, its sessions keep
            # their journals; rejoining remaps its targets back and
            # releases them from the survivor (live migration)
            mgr.restart_worker(router, "w0")
            for ln in lines[cut:]:
                router.feed(ln, reply=_reply)
            if not router.drain(timeout=900):
                raise RuntimeError("restart leg did not drain")
            # the aggregated stats fan-out (repeatable serve-status
            # renders this shape): every live worker rides along
            stats_reply = {}
            router.feed(json.dumps({"op": "stats", "id": "st1"}),
                        reply=lambda r: (stats_reply.update(r)))
            deadline = time.time() + 30
            while "fleet" not in stats_reply \
                    and time.time() < deadline:
                time.sleep(0.01)
        finally:
            mgr.shutdown(router)
            reporter.close()
        rejected = sorted(j for j, r in replies.items()
                          if r.get("status") == "REJECTED")
        if rejected or set(replies) != set(all_ids):
            raise RuntimeError(
                f"rolling restart lost jobs: "
                f"{sorted(set(all_ids) - set(replies))} missing, "
                f"{rejected} rejected")
        if len(stats_reply.get("workers") or {}) != 2:
            raise RuntimeError(
                f"stats fan-out answered with "
                f"{sorted(stats_reply.get('workers') or {})}, "
                f"want 2 workers")
        def _leg_spans(records):
            for r in records:
                if r.get("record") != "serve":
                    continue
                for d in (r.get("spans"), r.get("open_spans")):
                    if isinstance(d, dict):
                        yield d
        restart_records = read_records(mgr.out)
        compiled = [d for d in _leg_spans(restart_records)
                    if "compile_s" in d or "eval_compile_s" in d]
        if compiled:
            raise RuntimeError(
                f"rolling restart recompiled {len(compiled)} "
                f"span(s); warm dispatch must deserialize: "
                f"{compiled[0]}")
        if not any("deserialize_s" in d or "eval_deserialize_s" in d
                   for d in _leg_spans(restart_records)):
            raise RuntimeError(
                "restart leg shows no deserialize_s span; the "
                "shared-cache warm path did not run")
        restart_out = mgr.out

        # ---- kill -9 one worker mid-load: healthy jobs all
        # complete; the dead worker's warm session migrates and its
        # post-failover deltas are bit-exact vs the oracle
        mgr, router, reporter = start_fleet("kill", 2)
        replies = {}
        try:
            pre = [ln for ln in lines
                   if json.loads(ln)["id"].startswith("j")] \
                + [ln for ln in lines
                   if json.loads(ln)["id"] in delta_ids[:n_targets]]
            for ln in pre:
                router.feed(ln, reply=_reply_into(replies))
            if not router.drain(timeout=900):
                raise RuntimeError("kill leg warm phase stalled")
            victim = router._session_owner.get("j0")
            if victim is None:
                raise RuntimeError("kill leg: j0 has no owner")
            # a burst of cold solves in flight, then SIGKILL the
            # worker owning j0's warm session.  Solves are safely
            # resendable; the pending deltas come AFTER the kill so
            # the journal replay sequence matches the oracle exactly
            for ln in solve_lines:
                router.feed(ln, reply=_reply_into(replies))
            router.workers[victim].process.send_signal(
                _signal.SIGKILL)
            # wait for the router to notice the corpse before
            # feeding the post-kill deltas: a delta sent into the
            # victim's dying socket could be journaled-but-unreplied
            # and its re-send would double-apply, breaking the
            # oracle comparison; solves don't care
            deadline = time.time() + 60
            while victim in router.live_workers() \
                    and time.time() < deadline:
                time.sleep(0.02)
            post_deltas = [ln for ln in lines
                           if json.loads(ln)["id"] in
                           delta_ids[n_targets:]]
            for ln in post_deltas:
                router.feed(ln, reply=_reply_into(replies))
            if not router.drain(timeout=900):
                raise RuntimeError("kill leg did not drain")
        finally:
            mgr.shutdown(router)
            reporter.close()
        fed_ids = {json.loads(ln)["id"]
                   for ln in pre + solve_lines + post_deltas}
        rejected = sorted(j for j, r in replies.items()
                          if r.get("status") == "REJECTED")
        if rejected or set(replies) != fed_ids:
            raise RuntimeError(
                f"kill -9 lost healthy jobs: "
                f"{sorted(fed_ids - set(replies))} missing, "
                f"{rejected} rejected")
        if router.stats["failovers"] < 1:
            raise RuntimeError("kill leg recorded no failover")
        migrated = [j for j in delta_ids[n_targets:]
                    if json.loads(lines[all_ids.index(j)])
                    ["target"] == "j0"]
        if not migrated:
            raise RuntimeError(
                "kill leg has no post-failover deltas for j0; "
                "regenerate the stream")
        for jid in migrated:
            got, want = replies[jid], oracle[jid]
            if (got.get("assignment") != want.get("assignment")
                    or got.get("cost") != want.get("cost")
                    or got.get("cycle") != want.get("cycle")):
                raise RuntimeError(
                    f"migrated session diverged on {jid}: "
                    f"{got.get('cost')}/{got.get('cycle')} vs "
                    f"oracle {want.get('cost')}/"
                    f"{want.get('cycle')}")

        # ---- trace reconstruction (ISSUE 20): every failed-over
        # job's records — router audit, both workers' JSONL, the dead
        # worker's flight-recorder spill — must stitch back into ONE
        # connected span tree from the telemetry directory alone
        from pydcop_tpu.observability.tracing import (
            assemble, load_telemetry_dir)

        tele_recs, tele_spills = load_telemetry_dir(mgr.fleet_dir)
        failover_links = [
            r for r in tele_recs
            if r.get("record") == "trace"
            and r.get("event") == "link"
            and (r.get("link") or {}).get("kind") == "failover"]
        if not failover_links:
            raise RuntimeError(
                "kill leg wrote no failover link span; the re-sent "
                "jobs' trees cannot be joined")
        for link in failover_links:
            roots = assemble(tele_recs, tele_spills,
                             link["trace_id"])
            if len(roots) != 1:
                raise RuntimeError(
                    f"trace {link['trace_id']} reassembled to "
                    f"{len(roots)} roots; a failed-over job must "
                    f"be ONE connected tree")

        return {
            "metric": f"serve_fleet_{n_jobs}job_"
                      f"{max(worker_counts)}w",
            "value": {
                "jobs_s_1w": base,
                "scaling": scaling,
                "cores": cores,
                "rolling_restart": {
                    "lost_jobs": 0, "recompiles": 0,
                    "out": restart_out},
                "kill9": {
                    "victim": victim,
                    "failovers": router.stats["failovers"],
                    "resent": router.stats["resent"],
                    "migrated_deltas_bitexact": len(migrated),
                    "trace_trees_connected": len(failover_links),
                    "out": mgr.out},
                "outs": {f"{n}w": legs[n]["out"]
                         for n in worker_counts},
            },
            "unit": "jobs/s scale-out + restart/failover contracts",
            "contracts_asserted": True,
            "hardware": jax.default_backend(),
        }
    finally:
        if not keep:
            shutil.rmtree(work, ignore_errors=True)


def _reply_into(replies):
    def _r(rec):
        replies[rec.get("job_id") or rec.get("id")] = rec
    return _r


def bench_obs_overhead(quick=False):
    """The observability tax A/B (ISSUE 20): the SAME mixed job
    burst through stdin ``serve`` daemons with the full ops plane ON
    (metrics registry + heartbeat-cadence SLO evaluation + flight
    recorder) vs OFF (``--no-metrics --no-flightrec``), both warm
    against one shared executable cache.  The measured legs
    INTERLEAVE (bare, obs, bare, obs, ...) and each side keeps its
    best serving uptime — the bench_telemetry_overhead discipline:
    one-leg-per-phase A/Bs on a shared host measure scheduling
    drift, not instrumentation.  Contracts: the obs leg actually
    exercised the machinery (slo records emitted, a flight-recorder
    spill on disk), and (full run) the throughput overhead is under
    5% — at --quick's job count the shared fixed costs dominate the
    ratio, so quick smoke-tests the machinery only."""
    import os
    import shutil
    import subprocess
    import tempfile

    n_jobs = 60 if quick else 240
    reps = 2 if quick else 3
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    work = tempfile.mkdtemp(prefix="pydcop_obs_")
    try:
        from pydcop_tpu.dcop.yamldcop import dcop_yaml
        from pydcop_tpu.generators.graphcoloring import \
            generate_graph_coloring
        from pydcop_tpu.observability.report import read_records

        paths = []
        for nv in (12, 14, 16):
            dcop = generate_graph_coloring(
                nv, 3, "scalefree", m_edge=2, soft=True, seed=nv)
            p = os.path.join(work, f"i{nv}.yaml")
            with open(p, "w") as f:
                f.write(dcop_yaml(dcop))
            paths.append(p)
        jobs_text = "".join(json.dumps({
            "id": f"j{i}", "dcop": paths[i % len(paths)],
            "algo": "maxsum" if i % 2 else "dsa",
            "max_cycles": 10, "seed": i}) + "\n"
            for i in range(n_jobs))
        slo_file = os.path.join(work, "slo.yaml")
        with open(slo_file, "w") as f:
            f.write("objectives:\n"
                    "  - {name: p99, kind: latency_p99, target: 60}\n"
                    "  - {name: errs, kind: error_rate, target: 0.5}\n"
                    "  - {name: depth, kind: queue_depth, "
                    "target: 10000}\n")
        exec_dir = os.path.join(work, "exec")

        def run_daemon(tag, run_i, extra):
            out_dir = os.path.join(work, f"{tag}_{run_i}")
            os.makedirs(out_dir, exist_ok=True)
            out = os.path.join(out_dir, "out.jsonl")
            proc = subprocess.run(
                [sys.executable, "-m", "pydcop_tpu.dcop_cli",
                 "serve", "--out", out, "--exec-cache", exec_dir,
                 "--max-batch", "8", "--max-delay-ms", "5",
                 *extra],
                input=jobs_text, capture_output=True, text=True,
                timeout=1800, env=env, cwd=repo)
            if proc.returncode != 0:
                raise RuntimeError(f"{tag} rc={proc.returncode}: "
                                   f"{proc.stderr[-300:]}")
            records = read_records(out)
            final = records[-1]
            if final.get("event") != "drained":
                raise RuntimeError(f"{tag} did not drain: {final}")
            done = sum(1 for r in records
                       if r.get("record") == "summary"
                       and r.get("status") != "REJECTED")
            if done != n_jobs:
                raise RuntimeError(f"{tag} completed {done}/{n_jobs}")
            return float(final["uptime_s"]), records, out_dir

        obs_extra = ["--slo", slo_file, "--heartbeat-s", "0.2"]
        bare_extra = ["--no-metrics", "--no-flightrec"]
        run_daemon("warmup", 0, bare_extra)  # compile into exec_dir
        bare_times, obs_times = [], []
        obs_records, obs_dir = None, None
        for i in range(reps):
            t, _, _ = run_daemon("bare", i, bare_extra)
            bare_times.append(t)
            t, obs_records, obs_dir = run_daemon("obs", i, obs_extra)
            obs_times.append(t)
        spill = [n for n in os.listdir(obs_dir)
                 if n.startswith("flightrec-")]
        if not spill:
            raise RuntimeError(
                "obs leg left no flight-recorder spill beside --out")
        slo_recs = [r for r in obs_records
                    if r.get("record") == "slo"]
        if not slo_recs:
            raise RuntimeError(
                "obs leg emitted no slo records (heartbeat SLO "
                "evaluation did not run)")
        overhead = min(obs_times) / min(bare_times) - 1.0
        if overhead >= 0.05 and not quick:
            raise RuntimeError(
                f"observability contract violated: {overhead:.1%} "
                f"throughput overhead with flight recorder + SLO "
                f"engine on (budget < 5%)")
        return {
            "metric": f"obs_overhead_{n_jobs}job",
            "value": {
                "bare_uptime_s": round(min(bare_times), 3),
                "obs_uptime_s": round(min(obs_times), 3),
                "overhead": round(overhead, 4),
                "slo_records": len(slo_recs),
            },
            "unit": "serving uptime ratio",
            "contracts_asserted": not quick,
            "hardware": "cpu-host",
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def bench_autotune(quick=False):
    """The ISSUE 18 contract: autotune a small rung ladder on host
    CPU through the real batched runners, then A/B tuned-vs-default
    dispatch.  Asserted, not eyeballed:

    * never-slower on EVERY rung: the winner's measured ms/cycle is
      <= the default's — an arithmetic identity of the search (the
      final argmin always contains the default's own full-budget
      measurement), re-checked here against the persisted tables;
    * a measured speedup (> 1.0x) on at least one rung — the tuner
      must be able to FIND wins, not just avoid losses; on a tie-
      heavy host the ladder grows extra rungs before giving up;
    * an A/B re-measure of tuned-vs-default dispatch per rung stays
      inside a 1.5x noise envelope of never-slower (host-CPU timer
      jitter gets slack; a gross inversion still fails);
    * bit-exactness: dispatch resolving the winner from the sidecar
      and dispatch pinning the same config explicitly produce
      IDENTICAL decoded selections and cycle counts from separately
      built runners — the autotuner changes which proven-exact
      config runs, never the arithmetic.

    Host-CPU numbers, labeled."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    import pydcop_tpu.parallel.batch as pbatch
    from pydcop_tpu.generators.fast import (coloring_factor_arrays,
                                            coloring_hypergraph_arrays)
    from pydcop_tpu.parallel.bucketing import (ShapeProfile,
                                               home_rung)
    from pydcop_tpu.tuning.autotune import (autotune,
                                            measure_ms_per_cycle)
    from pydcop_tpu.tuning.store import TunedConfigStore

    # the quick leg still needs a sane measurement budget: at ~4-cycle
    # stages the stage-1 ranking is timer noise, the search crowns a
    # noise winner, and the A/B re-measure below (rightly) calls the
    # inversion out
    cycles = 24 if quick else 32
    repeats = 2 if quick else 3
    batch = 2 if quick else 4

    def factor_set(nv, n_edges, seed0):
        insts = [coloring_factor_arrays(nv, n_edges, 3,
                                        seed=seed0 + i, noise=0.05)
                 for i in range(batch)]
        rung = home_rung(ShapeProfile.of(insts[0]))
        return ("maxsum", rung, [rung.pad(a) for a in insts])

    def hyper_set(nv, n_edges, seed0):
        insts = [coloring_hypergraph_arrays(nv, n_edges, 3,
                                            seed=seed0 + i)
                 for i in range(batch)]
        rung = home_rung(ShapeProfile.of(insts[0]))
        return ("dsa", rung, [rung.pad(a) for a in insts])

    ladder = [factor_set(12, 22, 1), factor_set(24, 50, 7),
              hyper_set(16, 30, 3)]
    if not quick:
        ladder.append(factor_set(48, 100, 11))
    work = tempfile.mkdtemp(prefix="pydcop_autotune_")
    try:
        store = TunedConfigStore(path=work)
        results = autotune(
            [(algo, rung.signature, insts)
             for algo, rung, insts in ladder],
            cycles=cycles, repeats=repeats, store=store)
        # a tie-heavy host (every winner == default) grows the ladder
        # before the speedup assertion: the contract is "can find
        # wins", not "wins on these three seeds"
        extra_seeds = iter((23, 31, 47))
        while not any(r["speedup_vs_default"] > 1.0
                      for r in results):
            seed = next(extra_seeds, None)
            if seed is None:
                break
            extra = factor_set(18, 36, seed)
            ladder.append(extra)
            results += autotune(
                [(extra[0], extra[1].signature, extra[2])],
                cycles=cycles, repeats=repeats, store=store)
        rows = []
        for r in results:
            if r["best_ms_per_cycle"] > r["default_ms_per_cycle"]:
                raise RuntimeError(
                    f"never-slower violated on {r['rung_label']}: "
                    f"best {r['best_ms_per_cycle']} > default "
                    f"{r['default_ms_per_cycle']} ms/cycle")
            rows.append({
                "algo": r["algo"], "rung": r["rung_label"],
                "best": r["best_label"],
                "best_ms_per_cycle": r["best_ms_per_cycle"],
                "default_ms_per_cycle": r["default_ms_per_cycle"],
                "speedup": r["speedup_vs_default"],
                "candidates": r["candidates"],
                "pruned": r["pruned"],
            })
        if not any(row["speedup"] > 1.0 for row in rows):
            raise RuntimeError(
                f"no rung measured a speedup over default across "
                f"{len(rows)} rungs; the tuner found no wins")

        # ---- A/B re-measure: tuned dispatch vs forced-default
        # dispatch, warm, per rung (1.5x envelope on CPU timer noise).
        # A single re-measure still inverts every ~10th quick run on a
        # loaded host — a noise spike lands on the tuned leg alone —
        # so an apparent inversion gets ONE fresh A/B pair before the
        # contract fails; a real inversion reproduces, a spike doesn't.
        for (algo, rung, insts), row in zip(ladder, rows):
            entry = store.load(algo, rung.signature)
            for attempt in range(2):
                tuned_ms = measure_ms_per_cycle(
                    algo, insts, dict(entry["best"]), rung.signature,
                    cycles=cycles, repeats=max(2, repeats))
                default_ms = measure_ms_per_cycle(
                    algo, insts, {}, rung.signature,
                    cycles=cycles, repeats=max(2, repeats))
                if tuned_ms <= default_ms * 1.5:
                    break
                print(f"[bench_autotune] A/B inversion on "
                      f"{row['rung']} (tuned {tuned_ms:.4f} vs "
                      f"default {default_ms:.4f} ms/cycle), "
                      f"re-measuring once")
            row["ab_tuned_ms_per_cycle"] = round(tuned_ms, 4)
            row["ab_default_ms_per_cycle"] = round(default_ms, 4)
            if tuned_ms > default_ms * 1.5:
                raise RuntimeError(
                    f"A/B inversion on {row['rung']}: tuned "
                    f"{tuned_ms:.4f} vs default {default_ms:.4f} "
                    f"ms/cycle (reproduced on re-measure)")

        # ---- bit-exactness: sidecar-resolved dispatch == the same
        # config pinned explicitly, from SEPARATELY built runners
        algo, rung, insts = ladder[0]
        best = store.load(algo, rung.signature)["best"]
        seeds = list(range(len(insts)))
        pbatch._RUNNER_CACHE.clear()
        r_tuned = pbatch.runner_for_rung(
            algo, insts, {}, rung_signature=rung.signature,
            tuned_store=store)
        sel_t, cyc_t, _f = r_tuned.run(max_cycles=cycles,
                                       seeds=seeds)
        dec_t = r_tuned.decode(sel_t)
        pbatch._RUNNER_CACHE.clear()
        r_exp = pbatch.runner_for_rung(
            algo, insts, dict(best), rung_signature=rung.signature)
        sel_e, cyc_e, _f = r_exp.run(max_cycles=cycles, seeds=seeds)
        dec_e = r_exp.decode(sel_e)
        if r_exp is r_tuned:
            raise RuntimeError(
                "bit-exactness leg reused one runner; the cache "
                "clear failed and the comparison proves nothing")
        for i in range(len(insts)):
            if not np.array_equal(dec_t[i], dec_e[i]) \
                    or int(cyc_t[i]) != int(cyc_e[i]):
                raise RuntimeError(
                    f"tuned dispatch diverged from the explicit "
                    f"spelling of {best} on instance {i}")

        return {
            "metric": f"autotune_{len(rows)}rung_ladder",
            "value": {
                "rungs": rows,
                "store": {k: store.stats[k]
                          for k in ("stores", "hits")},
                "max_speedup": max(row["speedup"] for row in rows),
                "bit_exact_config": dict(best),
            },
            "unit": "ms/cycle tuned vs default per rung",
            "contracts_asserted": True,
            "hardware": jax.default_backend(),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


BENCHES = [bench_solve_api_small, bench_amaxsum_1k,
           bench_dpop_device_widetree, bench_dpop_sharded_util,
           bench_dpop_meetings, bench_localsearch_10k, bench_batched,
           bench_mixed_hard_constraints, bench_batched_localsearch,
           bench_batch_campaign_fused, bench_nary_fastpath,
           bench_mesh_dispatch, bench_hetero_batch, bench_precision,
           bench_telemetry_overhead, bench_decimation,
           bench_bnb_pruning, bench_serve, bench_dynamic,
           bench_roi, bench_portfolio, bench_serve_dynamic,
           bench_chaos, bench_autotune, bench_fleet,
           bench_obs_overhead]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("benches", nargs="*", metavar="BENCH",
                        help="run only these benchmarks by function "
                             "name (e.g. bench_decimation); default: "
                             "the full suite")
    parser.add_argument("--quick", action="store_true",
                        help="smaller sizes (CI-friendly)")
    parser.add_argument("--out", default=None,
                        help="also write the JSONL results here "
                             "(default: BENCH_SUITE.json next to this "
                             "script's repo root, unless --quick)")
    args = parser.parse_args()
    benches = BENCHES
    if args.benches:
        by_name = {b.__name__: b for b in BENCHES}
        unknown = [n for n in args.benches if n not in by_name]
        if unknown:
            parser.error(f"unknown benchmark(s) {unknown}; choose "
                         f"from {sorted(by_name)}")
        benches = [by_name[n] for n in args.benches]
    results = []
    for bench in benches:
        try:
            if "quick" in bench.__code__.co_varnames:
                r = bench(quick=args.quick)
            else:
                r = bench()
        except Exception as e:  # keep the suite running
            r = {"metric": bench.__name__, "error": repr(e)}
        results.append(r)
        print(json.dumps(r))
    ok = sum(1 for r in results if "error" not in r)
    results.append({"suite": "baseline_configs", "ok": ok,
                    "total": len(results)})
    print(json.dumps(results[-1]))
    out = args.out
    if out is None and not args.quick and not args.benches \
            and ok == len(results) - 1:
        # only a fully-green run may replace the checked-in baseline;
        # a degraded run (dead accelerator -> error rows) must not
        # clobber the numbers README cites
        import os

        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_SUITE.json")
    if out:
        with open(out, "w") as f:
            f.write("\n".join(json.dumps(r) for r in results) + "\n")


if __name__ == "__main__":
    main()

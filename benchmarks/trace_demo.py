"""``make trace-demo``: the tracing pipeline end to end, narrated.

Runs the ``bench_fleet`` quick contract (real router, real worker
subprocesses, kill -9 failover leg included) keeping its telemetry
under the given directory, then does what an operator debugging a
failed-over job would do:

1. ``pydcop telemetry-validate <kill-leg dir>`` — every record green
   against schema 1.11, every trace parent reference resolving;
2. pick a failover link span out of the kill leg's shared JSONL;
3. ``pydcop trace <trace_id> --dir <kill-leg dir>`` — render the
   reassembled span tree (ONE connected tree: route span, the dead
   worker's spans, the failover link, the survivor's spans) with
   timing attribution.

Usage: ``python benchmarks/trace_demo.py [OUT_DIR]``
"""

import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))   # repo root: pydcop_tpu
sys.path.insert(0, _HERE)                    # benchmarks: suite.py


def main():
    out_dir = sys.argv[1] if len(sys.argv) > 1 \
        else "/tmp/pydcop_trace_demo"
    import suite as bench_suite  # noqa: E402 - sibling module

    from pydcop_tpu.dcop_cli import main as cli_main

    print(f"[trace-demo] running bench_fleet --quick into "
          f"{out_dir} (spawns real worker daemons; takes a few "
          f"minutes)", file=sys.stderr)
    result = bench_suite.bench_fleet(quick=True, out_dir=out_dir)
    kill_out = result["value"]["kill9"]["out"]
    kill_dir = os.path.dirname(kill_out)
    print(f"[trace-demo] kill -9 leg telemetry: {kill_dir}",
          file=sys.stderr)
    rc = cli_main(["telemetry-validate", kill_dir])
    if rc:
        return rc
    links = []
    with open(kill_out) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("record") == "trace" \
                    and (rec.get("link") or {}).get("kind") \
                    == "failover":
                links.append(rec)
    if not links:
        print("[trace-demo] no failover link span in the kill leg?!",
              file=sys.stderr)
        return 1
    tid = links[0]["trace_id"]
    print(f"[trace-demo] rendering failed-over trace {tid}:",
          file=sys.stderr)
    return cli_main(["trace", tid, "--dir", kill_dir])


if __name__ == "__main__":
    sys.exit(main())

"""Benchmark: MaxSum msgs/sec on 10k-variable graph coloring, TPU vs the
reference-architecture CPU-thread runtime.

North star (BASELINE.json): 10k-var graph-coloring MaxSum converging <1s
on one chip, >=100x the threaded CPU agent runtime at equal solution cost.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Message accounting follows the reference: one var->factor and one
factor->var message per edge per cycle (the reference counts each posted
message, SURVEY.md §3.3); the compiled engine moves 2*E messages per
jitted step, so msgs/sec = 2 * E * cycles / elapsed.
"""

import json
import sys
import time
from functools import partial

import numpy as np

N_VARS = 10_000
N_EDGES = 30_000
N_COLORS = 3
MEASURE_CYCLES = 60
BASELINE_SECONDS = 4.0
# threaded-baseline problem is smaller (the python runtime would need
# hours for 10k vars); per-message python cost is size-independent, so
# msgs/sec transfers
BASELINE_VARS = 1_000
BASELINE_EDGES = 3_000


def tpu_run():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from pydcop_tpu.algorithms.maxsum import MaxSumLaneSolver
    from pydcop_tpu.generators.fast import coloring_factor_arrays

    arrays = coloring_factor_arrays(
        N_VARS, N_EDGES, N_COLORS, seed=7, noise=0.05)
    # lane-major layout: edges in the 128-lane dim (1.5x edge-major)
    solver = MaxSumLaneSolver(arrays, damping=0.5, stability=0.0)

    # cycles per jitted call: on the tunneled chip, dispatch latency is
    # tens of ms, so one big on-device loop beats pipelined small chunks
    # (measured 46.7 -> 63.3 M msgs/s going from k=10 to k=60; the
    # while-loop still evaluates convergence every cycle on device)
    k = 60

    # donate the state pytree: the step is a pure in-place update, so
    # XLA reuses the message buffers instead of allocating per call
    # (measured 77.7 -> 87.6 M msgs/s on-chip)
    @partial(jax.jit, donate_argnums=0)
    def run_k(s):
        return jax.lax.fori_loop(0, k, lambda i, st: solver.step(st), s)

    state = solver.init_state(jax.random.PRNGKey(0))
    # warm-up / compile
    state = run_k(state)
    jax.block_until_ready(state["selection"])

    # best of 5: the tunneled chip shows heavy run-to-run contention
    # (observed 2x spread between whole-process runs)
    elapsed = float("inf")
    for _ in range(5):
        state = solver.init_state(jax.random.PRNGKey(0))
        t0 = time.perf_counter()
        cycles = 0
        while cycles < MEASURE_CYCLES:
            state = run_k(state)
            cycles += k
        jax.block_until_ready(state["selection"])
        elapsed = min(elapsed, time.perf_counter() - t0)

    sel = np.asarray(jax.device_get(state["selection"]))
    b = arrays.buckets[0]
    n_conflicts = int(np.sum(sel[b.var_ids[:, 0]] == sel[b.var_ids[:, 1]]))
    msgs = 2 * arrays.n_edges * cycles
    return msgs / elapsed, elapsed, cycles, n_conflicts


def cpu_baseline(best_of: int = 3):
    """Best-of-N like the TPU side: the host is contended too, and a
    single 4-second sample made vs_baseline swing 50% between rounds
    (75.9M/1136x in r01 vs 84.7M/734x in r02 — the TPU got *faster*
    while the ratio fell)."""
    sys.path.insert(0, "benchmarks")
    from cpu_baseline import run_maxsum_baseline

    from pydcop_tpu.generators.fast import random_graph_edges

    rng = np.random.default_rng(7)
    edges = random_graph_edges(BASELINE_VARS, BASELINE_EDGES, seed=7)
    var_costs = rng.uniform(0, 0.05, size=(BASELINE_VARS, N_COLORS))
    best_rate, conflicts = 0.0, None
    for _ in range(best_of):
        msgs, elapsed, n_conf = run_maxsum_baseline(
            edges.tolist(), BASELINE_VARS, N_COLORS, var_costs,
            duration=BASELINE_SECONDS)
        rate = msgs / elapsed
        if rate > best_rate:
            best_rate = rate
        # conflicts after a full-duration run (any run: converged state)
        conflicts = n_conf if conflicts is None else min(conflicts,
                                                        n_conf)
    return best_rate, conflicts


def tpu_run_guarded(budget_s: float = 900.0):
    """Run the TPU side in a child process with a hard wall-clock cap.

    The tunneled chip has been observed to hang indefinitely (even
    device enumeration stalls for hours); a hung bench records nothing
    at all, a guarded one records an explicit failure."""
    import subprocess

    code = (
        "import json, bench\n"
        "r = bench.tpu_run()\n"
        "print('BENCH_RESULT ' + json.dumps(list(r)))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=budget_s)
        for line in proc.stdout.splitlines():
            if line.startswith("BENCH_RESULT "):
                vals = json.loads(line[len("BENCH_RESULT "):])
                return tuple(vals), None
        return None, (proc.stderr.strip().splitlines() or ["no output"]
                      )[-1][:200]
    except subprocess.TimeoutExpired:
        return None, f"tpu unreachable (no result in {budget_s:.0f}s)"


def main():
    tpu, err = tpu_run_guarded()
    if tpu is None:
        print(json.dumps({
            "metric": "maxsum_msgs_per_sec_10kvar_coloring",
            "value": 0.0,
            "unit": "msgs/s",
            "vs_baseline": 0.0,
            "error": err,
        }))
        return
    tpu_msgs_per_sec, elapsed, cycles, tpu_conflicts = tpu
    cpu_msgs_per_sec, cpu_conflicts = cpu_baseline()
    vs = tpu_msgs_per_sec / cpu_msgs_per_sec if cpu_msgs_per_sec else 0.0
    # the BASELINE.md claim is ">=100x at equal solution cost": compare
    # conflict *rates* (the instances differ in size: 30k vs 3k edges)
    tpu_rate = tpu_conflicts / N_EDGES
    cpu_rate = (cpu_conflicts / BASELINE_EDGES
                if cpu_conflicts is not None else 1.0)
    print(json.dumps({
        "metric": "maxsum_msgs_per_sec_10kvar_coloring",
        "value": round(tpu_msgs_per_sec, 1),
        "unit": "msgs/s",
        "vs_baseline": round(vs, 2),
        "tpu_conflicts": tpu_conflicts,
        "tpu_conflict_rate": round(tpu_rate, 5),
        "cpu_conflicts": cpu_conflicts,
        "cpu_conflict_rate": round(cpu_rate, 5),
        "cost_parity": bool(tpu_rate <= cpu_rate + 0.005),
    }))


if __name__ == "__main__":
    main()

"""Benchmark: MaxSum msgs/sec on 10k-variable graph coloring, TPU vs the
reference-architecture CPU-thread runtime.

North star (BASELINE.json): 10k-var graph-coloring MaxSum converging <1s
on one chip, >=100x the threaded CPU agent runtime at equal solution cost.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Message accounting follows the reference: one var->factor and one
factor->var message per edge per cycle (the reference counts each posted
message, SURVEY.md §3.3); the compiled engine moves 2*E messages per
jitted step, so msgs/sec = 2 * E * cycles / elapsed.

Outage resilience: the tunneled chip has been observed to hang
indefinitely (even device enumeration stalls).  The device probe is
watchdogged and retried; on failure the artifact still carries the
compiled engine's CPU-mirror throughput with ``"hardware":
"unavailable"`` — never a bare zero.
"""

import json
import os
import signal
import subprocess
import sys
import time
from functools import partial

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))

N_VARS = 10_000
N_EDGES = 30_000
N_COLORS = 3
MEASURE_CYCLES = 60
CONV_MAX_CYCLES = 512
CONV_PLATEAU = 64  # cycles without anytime-cost improvement = stable
BASELINE_SECONDS = 4.0
# threaded-baseline problem is smaller (the python runtime would need
# hours for 10k vars); per-message python cost is size-independent, so
# msgs/sec transfers
BASELINE_VARS = 1_000
BASELINE_EDGES = 3_000


def _build(stability: float):
    sys.path.insert(0, REPO)
    from pydcop_tpu.algorithms.maxsum import (MaxSumFusedSolver,
                                              MaxSumLaneSolver)
    from pydcop_tpu.generators.fast import coloring_factor_arrays

    arrays = coloring_factor_arrays(
        N_VARS, N_EDGES, N_COLORS, seed=7, noise=0.05)
    # lane-major layout: edges in the 128-lane dim (1.5x edge-major).
    # PYDCOP_BENCH_LAYOUT=fused switches to the var-sorted one-gather
    # layout; flip the default once an on-chip A/B
    # (benchmarks/ab_variants.py) proves it faster there
    cls = MaxSumFusedSolver \
        if os.environ.get("PYDCOP_BENCH_LAYOUT") == "fused" \
        else MaxSumLaneSolver
    return arrays, cls(arrays, damping=0.5, stability=stability)


def _conflicts(arrays, sel):
    b = arrays.buckets[0]
    return int(np.sum(sel[b.var_ids[:, 0]] == sel[b.var_ids[:, 1]]))


def tpu_run(best_of: int = 5):
    """Throughput leg: convergence detection disabled (stability=0), the
    pure message-update rate the headline tracks."""
    import jax

    arrays, solver = _build(stability=0.0)

    # cycles per jitted call: on the tunneled chip, dispatch latency is
    # tens of ms, so one big on-device loop beats pipelined small chunks
    # (measured 46.7 -> 63.3 M msgs/s going from k=10 to k=60; the
    # while-loop still evaluates convergence every cycle on device)
    k = 60

    # donate the state pytree: the step is a pure in-place update, so
    # XLA reuses the message buffers instead of allocating per call
    # (measured 77.7 -> 87.6 M msgs/s on-chip)
    @partial(jax.jit, donate_argnums=0)
    def run_k(s):
        return jax.lax.fori_loop(0, k, lambda i, st: solver.step(st), s)

    state = solver.init_state(jax.random.PRNGKey(0))
    # warm-up / compile
    state = run_k(state)
    jax.block_until_ready(state["selection"])

    # best of N: the tunneled chip shows heavy run-to-run contention
    # (observed 2x spread between whole-process runs); same-program
    # best-of is unaffected by the first-compiled-program bias
    elapsed = float("inf")
    for _ in range(best_of):
        state = solver.init_state(jax.random.PRNGKey(0))
        t0 = time.perf_counter()
        cycles = 0
        while cycles < MEASURE_CYCLES:
            state = run_k(state)
            cycles += k
        jax.block_until_ready(state["selection"])
        elapsed = min(elapsed, time.perf_counter() - t0)

    # stability=0 elides the per-cycle argmin (r4): decode the live
    # selection from the final messages, never the stale state field
    sel = np.asarray(jax.device_get(solver.assignment_indices(state)))
    n_conflicts = _conflicts(arrays, sel)
    msgs = 2 * arrays.n_edges * cycles
    return msgs / elapsed, elapsed, cycles, n_conflicts


def convergence_run(best_of: int = 3):
    """North-star leg (VERDICT r4 item 3): seconds until the 10k-var
    instance's solution quality is stable, in ONE on-device while_loop
    dispatch.

    "Stable" is the anytime-cost plateau — the best decoded conflict
    count unchanged for CONV_PLATEAU consecutive cycles — because
    message-level SAME_COUNT quiescence never happens on this
    (deliberately frustrated) instance: measured on CPU, the best
    assignment lands at cycle ~11 and the message deltas oscillate
    forever after (benchmarks/PERF_NOTES.md round-5).  The reference's
    own notion of progress on such instances is the same anytime cost
    curve (orchestrator cost traces), so the plateau is the honest
    equivalent of its convergence."""
    import jax
    import jax.numpy as jnp

    arrays, solver = _build(stability=0.0)
    b = arrays.buckets[0]
    u = jnp.asarray(b.var_ids[:, 0])
    v = jnp.asarray(b.var_ids[:, 1])

    def cond(carry):
        s, best, since = carry
        return jnp.logical_and(since < CONV_PLATEAU,
                               s["cycle"] < CONV_MAX_CYCLES)

    def body(carry):
        s, best, since = carry
        s = solver.step(s)
        sel = solver.assignment_indices(s)
        conf = jnp.sum(sel[u] == sel[v]).astype(jnp.int32)
        improved = conf < best
        return (s, jnp.minimum(best, conf),
                jnp.where(improved, 0, since + 1))

    @jax.jit
    def run_to_plateau(s):
        return jax.lax.while_loop(
            cond, body, (s, jnp.int32(2**30), jnp.int32(0)))

    out = run_to_plateau(solver.init_state(jax.random.PRNGKey(0)))
    jax.block_until_ready(out[1])  # warm-up / compile

    elapsed = float("inf")
    for _ in range(best_of):
        s0 = solver.init_state(jax.random.PRNGKey(0))
        jax.block_until_ready(s0["q"])
        t0 = time.perf_counter()
        state, best_conf, since = run_to_plateau(s0)
        jax.block_until_ready(best_conf)
        elapsed = min(elapsed, time.perf_counter() - t0)

    return (elapsed, int(state["cycle"]),
            bool(int(since) >= CONV_PLATEAU), int(best_conf))


def cpu_baseline(best_of: int = 3):
    """Best-of-N like the TPU side: the host is contended too, and a
    single 4-second sample made vs_baseline swing 50% between rounds
    (75.9M/1136x in r01 vs 84.7M/734x in r02 — the TPU got *faster*
    while the ratio fell)."""
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    from cpu_baseline import run_maxsum_baseline

    from pydcop_tpu.generators.fast import random_graph_edges

    rng = np.random.default_rng(7)
    edges = random_graph_edges(BASELINE_VARS, BASELINE_EDGES, seed=7)
    var_costs = rng.uniform(0, 0.05, size=(BASELINE_VARS, N_COLORS))
    best_rate, conflicts = 0.0, None
    for _ in range(best_of):
        msgs, elapsed, n_conf = run_maxsum_baseline(
            edges.tolist(), BASELINE_VARS, N_COLORS, var_costs,
            duration=BASELINE_SECONDS)
        rate = msgs / elapsed
        if rate > best_rate:
            best_rate = rate
        # conflicts after a full-duration run (any run: converged state)
        conflicts = n_conf if conflicts is None else min(conflicts,
                                                        n_conf)
    return best_rate, conflicts


# --------------------------------------------------------------------
# watchdogged child-process plumbing
# --------------------------------------------------------------------

_CHILD_CODE = (
    "import json, bench\n"
    "t = bench.tpu_run(best_of={best_of})\n"
    "c = bench.convergence_run(best_of={conv_best_of})\n"
    "print('BENCH_RESULT ' + json.dumps([list(t), list(c)]))\n"
)


def _watchdogged(argv, budget_s, env=None):
    """Run ``argv`` with a HARD deadline: the child gets its own
    session, and on timeout the whole process GROUP is SIGKILLed.

    ``subprocess.run(timeout=...)`` kills only the direct child; a hung
    TPU runtime keeps helper threads/grandchildren alive holding the
    stdout/stderr pipes, so the parent's post-kill ``communicate()``
    blocks past the nominal budget — exactly the BENCH_r04/r05 probe
    hang.  Returns ``(stdout, stderr, timed_out)``."""
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, cwd=REPO, env=env, start_new_session=True)
    try:
        out, errout = proc.communicate(timeout=budget_s)
        return out, errout, False
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        # the group is dead: nothing holds the pipes, this returns
        out, errout = proc.communicate()
        return out or "", errout or "", True


def _run_child(env, budget_s, best_of, conv_best_of):
    code = _CHILD_CODE.format(best_of=best_of,
                              conv_best_of=conv_best_of)
    out, errout, timed_out = _watchdogged(
        [sys.executable, "-c", code], budget_s, env=env)
    if timed_out:
        return None, f"no result in {budget_s:.0f}s"
    for line in out.splitlines():
        if line.startswith("BENCH_RESULT "):
            tpu, conv = json.loads(line[len("BENCH_RESULT "):])
            return (tuple(tpu), tuple(conv)), None
    return None, (errout.strip().splitlines() or ["no output"]
                  )[-1][:200]


def probe_device(attempts: int = 2, budget_s: float = 45.0):
    """Bounded device probe: `jax.devices()` through the tunnel hangs
    forever when the tunnel is down, so never call it in-process (and
    kill the probe's whole process group on timeout — see
    :func:`_watchdogged`).  Prerequisite: the tunnel/plugin setup in
    ``provisioning/README.md``."""
    err = None
    for _ in range(attempts):
        out, errout, timed_out = _watchdogged(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print('NDEV', len(d), d[0].platform)"], budget_s)
        if timed_out:
            err = f"device probe hung ({budget_s:.0f}s)"
            continue
        for line in out.splitlines():
            if not line.startswith("NDEV"):
                continue
            platform = line.split()[-1].lower()
            # a fast-FAILING plugin falls back to the host backend:
            # that is an outage, not hardware — never label a CPU
            # run "tpu"
            if platform == "cpu":
                return False, f"probe found only {platform} devices"
            return True, None
        else:
            err = (errout.strip().splitlines() or ["no output"]
                   )[-1][:200]
    return False, err


def measure_accelerator():
    """Returns ``(results, hardware, probe_error, error)``: hardware is
    "tpu" or "unavailable" (results then come from the CPU mirror).
    ``probe_error`` is the structured reason the device probe/run leg
    failed; ``error`` is a CPU-mirror failure, if any."""
    ok, probe_err = probe_device()
    if ok:
        results, err = _run_child(None, budget_s=900.0, best_of=5,
                                  conv_best_of=3)
        if results is not None:
            return results, "tpu", None, None
        probe_err = err
    # CPU mirror: the same compiled program on the host backend.
    # JAX_PLATFORMS=cpu alone does NOT stop the axon plugin from
    # grabbing the backend — PYTHONPATH must also carry the repo root
    # (empirical; tests/conftest.py works around the same issue).
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    results, err = _run_child(env, budget_s=240.0, best_of=1,
                              conv_best_of=1)
    if results is not None:
        return results, "unavailable", probe_err, None
    return None, "unavailable", probe_err, f"cpu mirror: {err}"


def main():
    results, hardware, probe_err, err = measure_accelerator()
    if results is None:
        # even the CPU mirror failed: emit the explicit failure record
        rec = {
            "metric": "maxsum_msgs_per_sec_10kvar_coloring",
            "value": 0.0,
            "unit": "msgs/s",
            "vs_baseline": 0.0,
            "hardware": "unavailable",
            "error": err,
        }
        if probe_err:
            rec["probe_error"] = probe_err
        print(json.dumps(rec))
        return
    (tpu_msgs_per_sec, elapsed, cycles, tpu_conflicts), \
        (conv_seconds, conv_cycles, conv_finished, conv_conflicts) = \
        results
    cpu_msgs_per_sec, cpu_conflicts = cpu_baseline()
    vs = tpu_msgs_per_sec / cpu_msgs_per_sec if cpu_msgs_per_sec else 0.0
    # the BASELINE.md claim is ">=100x at equal solution cost": compare
    # conflict *rates* (the instances differ in size: 30k vs 3k edges)
    tpu_rate = tpu_conflicts / N_EDGES
    cpu_rate = (cpu_conflicts / BASELINE_EDGES
                if cpu_conflicts is not None else 1.0)
    conv_rate = conv_conflicts / N_EDGES
    out = {
        "metric": "maxsum_msgs_per_sec_10kvar_coloring",
        "value": round(tpu_msgs_per_sec, 1),
        "unit": "msgs/s",
        "vs_baseline": round(vs, 2),
        "hardware": hardware,
        "tpu_conflicts": tpu_conflicts,
        "tpu_conflict_rate": round(tpu_rate, 5),
        "cpu_conflicts": cpu_conflicts,
        "cpu_conflict_rate": round(cpu_rate, 5),
        "cost_parity": bool(tpu_rate <= cpu_rate + 0.005),
        # north star: seconds to a SAME_COUNT-stable fixed point on the
        # 10k-var instance (BASELINE.md: < 1 s on chip)
        "convergence_seconds": round(conv_seconds, 4),
        "convergence_cycles": conv_cycles,
        "convergence_reached": conv_finished,
        "convergence_conflict_rate": round(conv_rate, 5),
        "convergence_cost_parity": bool(conv_rate <= cpu_rate + 0.005),
    }
    if probe_err:
        # structured: why the hardware leg failed, NOT buried in a
        # generic error string (BENCH_r04/r05 triage ask)
        out["probe_error"] = probe_err
    if err:
        out["error"] = err
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Benchmark: MaxSum msgs/sec on 10k-variable graph coloring, TPU vs the
reference-architecture CPU-thread runtime.

North star (BASELINE.json): 10k-var graph-coloring MaxSum converging <1s
on one chip, >=100x the threaded CPU agent runtime at equal solution cost.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Message accounting follows the reference: one var->factor and one
factor->var message per edge per cycle (the reference counts each posted
message, SURVEY.md §3.3); the compiled engine moves 2*E messages per
jitted step, so msgs/sec = 2 * E * cycles / elapsed.
"""

import json
import sys
import time

import numpy as np

N_VARS = 10_000
N_EDGES = 30_000
N_COLORS = 3
MEASURE_CYCLES = 60
BASELINE_SECONDS = 4.0
# threaded-baseline problem is smaller (the python runtime would need
# hours for 10k vars); per-message python cost is size-independent, so
# msgs/sec transfers
BASELINE_VARS = 1_000
BASELINE_EDGES = 3_000


def tpu_run():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    from pydcop_tpu.algorithms.maxsum import MaxSumLaneSolver
    from pydcop_tpu.generators.fast import coloring_factor_arrays

    arrays = coloring_factor_arrays(
        N_VARS, N_EDGES, N_COLORS, seed=7, noise=0.05)
    # lane-major layout: edges in the 128-lane dim (1.5x edge-major)
    solver = MaxSumLaneSolver(arrays, damping=0.5, stability=0.0)

    # cycles per jitted call: on the tunneled chip, dispatch latency is
    # tens of ms, so one big on-device loop beats pipelined small chunks
    # (measured 46.7 -> 63.3 M msgs/s going from k=10 to k=60; the
    # while-loop still evaluates convergence every cycle on device)
    k = 60

    @jax.jit
    def run_k(s):
        return jax.lax.fori_loop(0, k, lambda i, st: solver.step(st), s)

    state = solver.init_state(jax.random.PRNGKey(0))
    # warm-up / compile
    state = run_k(state)
    jax.block_until_ready(state["selection"])

    # best of 5: the tunneled chip shows heavy run-to-run contention
    # (observed 2x spread between whole-process runs)
    elapsed = float("inf")
    for _ in range(5):
        state = solver.init_state(jax.random.PRNGKey(0))
        t0 = time.perf_counter()
        cycles = 0
        while cycles < MEASURE_CYCLES:
            state = run_k(state)
            cycles += k
        jax.block_until_ready(state["selection"])
        elapsed = min(elapsed, time.perf_counter() - t0)

    sel = np.asarray(jax.device_get(state["selection"]))
    b = arrays.buckets[0]
    n_conflicts = int(np.sum(sel[b.var_ids[:, 0]] == sel[b.var_ids[:, 1]]))
    msgs = 2 * arrays.n_edges * cycles
    return msgs / elapsed, elapsed, cycles, n_conflicts


def cpu_baseline():
    sys.path.insert(0, "benchmarks")
    from cpu_baseline import run_maxsum_baseline

    from pydcop_tpu.generators.fast import random_graph_edges

    rng = np.random.default_rng(7)
    edges = random_graph_edges(BASELINE_VARS, BASELINE_EDGES, seed=7)
    var_costs = rng.uniform(0, 0.05, size=(BASELINE_VARS, N_COLORS))
    msgs, elapsed = run_maxsum_baseline(
        edges.tolist(), BASELINE_VARS, N_COLORS, var_costs,
        duration=BASELINE_SECONDS)
    return msgs / elapsed


def main():
    tpu_msgs_per_sec, elapsed, cycles, n_conflicts = tpu_run()
    cpu_msgs_per_sec = cpu_baseline()
    vs = tpu_msgs_per_sec / cpu_msgs_per_sec if cpu_msgs_per_sec else 0.0
    print(json.dumps({
        "metric": "maxsum_msgs_per_sec_10kvar_coloring",
        "value": round(tpu_msgs_per_sec, 1),
        "unit": "msgs/s",
        "vs_baseline": round(vs, 2),
    }))


if __name__ == "__main__":
    main()

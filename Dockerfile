# Minimal runtime image for the distributed fabric (orchestrator and
# agent processes; the compute path needs jax — CPU wheels by default,
# swap the base image for a TPU VM image on real pods).
FROM python:3.12-slim

WORKDIR /opt/pydcop_tpu
COPY pyproject.toml .
COPY pydcop_tpu ./pydcop_tpu
RUN pip install --no-cache-dir "jax[cpu]" pyyaml numpy scipy networkx \
    websockets && pip install --no-cache-dir .

ENV JAX_PLATFORMS=cpu
ENTRYPOINT []
